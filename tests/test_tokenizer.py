"""Tokenizer stack tests — ported from src/tokenizer-test.cpp plus encode/
decode coverage using the synthetic byte-level tokenizer (the reference's
DEV_TESTS need a real llama3 tokenizer file, ours run against synthetic)."""

import pytest

from distributed_llama_multiusers_tpu.formats.synthetic import write_synthetic_tokenizer
from distributed_llama_multiusers_tpu.formats.tokenizer_file import TokenizerData
from distributed_llama_multiusers_tpu.tokenizer import (
    ChatItem,
    ChatTemplateGenerator,
    EosDetector,
    EosResult,
    Sampler,
    TemplateType,
    Tokenizer,
    TokenizerChatStops,
)

TEST_EOS_ID = 10000


@pytest.fixture(scope="module")
def tok(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tok") / "t.t")
    write_synthetic_tokenizer(path, vocab_size=128)
    return Tokenizer(path)


# ---- encode ---------------------------------------------------------------


def test_encode_bpe_merges(tok):
    # "hello" should merge up to the best-scoring pieces
    ids = tok.encode("hello world", add_bos=False, add_special_tokens=False)
    assert "".join(tok.vocab[i].decode() for i in ids) == "hello world"
    assert tok.vocab[ids[0]] == b"hello"
    # "world" merges via wo + rl + d -> world
    assert b"world" in [tok.vocab[i] for i in ids]


def test_encode_special_tokens(tok):
    text = "<|start_header_id|>user<|end_header_id|>hello<|eot_id|>"
    ids = tok.encode(text, add_bos=True, add_special_tokens=True)
    assert ids[0] == tok.bos_id
    pieces = [tok.vocab[i] for i in ids]
    assert b"<|start_header_id|>" in pieces
    assert b"<|eot_id|>" in pieces
    # specials not split into characters
    assert pieces.count(b"<") == 0


def test_encode_specials_disabled(tok):
    ids = tok.encode("<|eot_id|>", add_bos=False, add_special_tokens=False)
    assert tok.eos_token_ids[0] not in ids
    assert "".join(tok.vocab[i].decode() for i in ids) == "<|eot_id|>"


def test_encode_roundtrip_decode(tok):
    text = "hello world! (123)"
    ids = tok.encode(text, add_bos=True)
    assert tok.decode_full(ids) == text


# ---- streaming decode / UTF-8 recovery ------------------------------------


def make_emoji_tokenizer():
    """Vocab with partial-UTF8 pieces, mimicking llama3's byte-pair emoji
    split used by dev_testDecoderEmoji* (tokenizer-test.cpp:71-120)."""
    emoji = "😃".encode()  # f0 9f 98 83
    vocab = [b"!", b"Y", emoji[:3], emoji[3:], b"x"]
    scores = [0.0] * len(vocab)
    bos_id = len(vocab)
    vocab += [b"<|bos|>", b"<|eos|>"]
    scores += [0.0, 0.0]
    data = TokenizerData(
        vocab=vocab, scores=scores, bos_id=bos_id, eos_token_ids=[bos_id + 1],
        chat_template=None, max_token_length=max(len(v) for v in vocab),
    )
    return Tokenizer(data)


def test_decoder_emoji():
    t = make_emoji_tokenizer()
    assert t.decode(t.bos_id) is None
    assert t.decode(2) is None  # first 3 bytes of emoji held back
    assert t.decode(3) == "😃"
    assert t.decode(0) == "!"
    assert t.decode(1) == "Y"


def test_decoder_emoji_with_eos():
    t = make_emoji_tokenizer()
    assert t.decode(t.bos_id) is None
    assert t.decode(2) is None
    assert t.decode(3) == "😃"
    assert t.decode(t.eos_token_ids[0]) is None


def test_decoder_emoji_stream_recover():
    # two incomplete prefixes then a continuation: first prefix collapses to
    # U+FFFD, second completes (tokenizer-test.cpp:71-85)
    t = make_emoji_tokenizer()
    assert t.decode(t.bos_id) is None
    assert t.decode(2) is None
    assert t.decode(2) is None
    assert t.decode(3) == "�😃"


# ---- chat templates -------------------------------------------------------


def test_chat_template_detection():
    # tokenizer-test.cpp:122-127
    template = (
        "{% set loop_messages = messages %}{% for message in loop_messages %}"
        "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
        "+ message['content'] | trim + '<|eot_id|>' %}{{ content }}{% endfor %}"
    )
    g = ChatTemplateGenerator(TemplateType.UNKNOWN, template, "<eos>")
    assert g.type == TemplateType.LLAMA3


def test_chat_template_llama3_render():
    g = ChatTemplateGenerator(TemplateType.LLAMA3, None, "<|eot_id|>")
    out = g.generate(
        [ChatItem("system", "be nice"), ChatItem("user", "hi")],
        append_generation_prompt=True,
    )
    assert out.content == (
        "<|start_header_id|>system<|end_header_id|>\n\nbe nice<|eot_id|>"
        "<|start_header_id|>user<|end_header_id|>\n\nhi<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    )
    assert out.public_prompt is None


def test_chat_template_llama2_render():
    g = ChatTemplateGenerator(TemplateType.LLAMA2, None, "</s>")
    out = g.generate(
        [ChatItem("system", "S"), ChatItem("user", "U"), ChatItem("assistant", "A"), ChatItem("user", "U2")],
        append_generation_prompt=True,
    )
    assert out.content == "[INST] <<SYS>>\nS\n<</SYS>>\n\nU [/INST]</s>A</s>[INST] U2 [/INST]</s>"


def test_chat_template_deepseek3_render():
    g = ChatTemplateGenerator(TemplateType.DEEP_SEEK3, None, "<eos>")
    out = g.generate([ChatItem("user", "hi")], append_generation_prompt=True)
    assert out.content == "<｜User｜>hi<｜Assistant｜><think>\n"
    assert out.public_prompt == "<think>\n"


def test_tokenizer_chat_stops(tok):
    stops = TokenizerChatStops(tok)
    assert stops.stops == ["<|eot_id|>"]
    assert stops.max_stop_length == len("<|eot_id|>")


# ---- EosDetector (ports of tokenizer-test.cpp:129-303) --------------------


def test_eos_detector_with_padding():
    det = EosDetector([TEST_EOS_ID, TEST_EOS_ID + 1], ["<eos>", "<stop>"], 1, 1)

    assert det.append(1, "<") == EosResult.MAYBE_EOS
    assert det.append(2, "eo") == EosResult.MAYBE_EOS
    assert det.append(3, "s>") == EosResult.EOS
    assert det.get_delta() is None

    det.reset()
    assert det.append(1, "<") == EosResult.MAYBE_EOS
    assert det.append(2, "stop") == EosResult.MAYBE_EOS
    assert det.append(3, "> ") == EosResult.EOS
    assert det.get_delta() is None

    det.reset()
    assert det.append(1, " ") == EosResult.NOT_EOS
    assert det.get_delta() == " "

    det.reset()
    assert det.append(1, "!<") == EosResult.MAYBE_EOS
    assert det.append(2, "eos") == EosResult.MAYBE_EOS
    assert det.append(3, "> ") == EosResult.EOS
    assert det.get_delta() == "!"

    det.reset()
    assert det.append(1, "<eo") == EosResult.MAYBE_EOS
    assert det.append(2, "s>XY") == EosResult.NOT_EOS
    assert det.get_delta() == "<eos>XY"

    det.reset()
    assert det.append(1, "<eo") == EosResult.MAYBE_EOS
    assert det.append(TEST_EOS_ID, None) == EosResult.EOS
    assert det.get_delta() == "<eo"

    det.reset()
    assert det.append(TEST_EOS_ID, None) == EosResult.EOS
    assert det.get_delta() is None

    det.reset()
    assert det.append(1, "x") == EosResult.NOT_EOS
    assert det.get_delta() == "x"
    det.reset()
    assert det.append(2, None) == EosResult.NOT_EOS
    assert det.get_delta() is None


def test_eos_detector_with_long_padding():
    det = EosDetector([TEST_EOS_ID], ["|end|"], 5, 5)

    assert det.append(1, "lipsum") == EosResult.NOT_EOS
    assert det.get_delta() == "lipsum"

    det.reset()
    assert det.append(1, "lorem") == EosResult.NOT_EOS
    assert det.get_delta() == "lorem"

    det.reset()
    assert det.append(1, "lorem|") == EosResult.MAYBE_EOS
    assert det.append(2, "enQ") == EosResult.NOT_EOS
    assert det.get_delta() == "lorem|enQ"


def test_eos_detector_without_padding():
    det = EosDetector([TEST_EOS_ID], ["<eos>"], 0, 0)

    assert det.append(1, "<") == EosResult.MAYBE_EOS
    assert det.append(2, "eo") == EosResult.MAYBE_EOS
    assert det.append(3, "s>") == EosResult.EOS
    assert det.get_delta() is None

    det.reset()
    assert det.append(1, " <") == EosResult.NOT_EOS
    assert det.get_delta() == " <"

    det.reset()
    assert det.append(1, "<eos") == EosResult.MAYBE_EOS
    assert det.append(2, "> ") == EosResult.NOT_EOS
    assert det.get_delta() == "<eos> "

    det.reset()
    assert det.append(TEST_EOS_ID, None) == EosResult.EOS
    assert det.get_delta() is None

    det.reset()
    assert det.append(TEST_EOS_ID, "😃") == EosResult.EOS
    assert det.get_delta() == "😃"


# ---- sampler --------------------------------------------------------------


def test_sampler_greedy():
    import numpy as np

    s = Sampler(8, temperature=0.0, topp=0.9, rng_seed=42)
    logits = np.array([0.1, 5.0, 0.2, 0.3, -1, 0, 0, 0], dtype=np.float32)
    assert s.sample(logits) == 1


def test_sampler_seeded_reproducible():
    import numpy as np

    logits = np.linspace(-1, 1, 32).astype(np.float32)
    a = Sampler(32, 0.8, 0.9, rng_seed=7)
    b = Sampler(32, 0.8, 0.9, rng_seed=7)
    seq_a = [a.sample(logits) for _ in range(20)]
    seq_b = [b.sample(logits) for _ in range(20)]
    assert seq_a == seq_b
    assert len(set(seq_a)) > 1  # actually samples


def test_sampler_topp_restricts():
    import numpy as np

    logits = np.full(100, -10.0, dtype=np.float32)
    logits[0] = 10.0
    logits[1] = 9.0
    s = Sampler(100, temperature=1.0, topp=0.5, rng_seed=3)
    for _ in range(50):
        assert s.sample(logits.copy()) in (0, 1)


def test_sampler_xorshift_parity():
    # xorshift64* from src/tokenizer.cpp:25-31 with seed 12345: first values
    from distributed_llama_multiusers_tpu.tokenizer.sampler import _random_u32

    state = 12345
    vals = []
    for _ in range(4):
        v, state = _random_u32(state)
        vals.append(v)
    # computed with the exact C semantics (uint64 wraparound)
    s = 12345
    M = (1 << 64) - 1
    expect = []
    for _ in range(4):
        s ^= s >> 12
        s = (s ^ (s << 25)) & M
        s ^= s >> 27
        expect.append(((s * 0x2545F4914F6CDD1D) & M) >> 32)
    assert vals == expect


# ---- heap merge: order parity with the reference's rescan + latency bound --


def _merge_reference(tok: Tokenizer, tokens: list[int]) -> list[int]:
    """The reference's O(n^2) merge verbatim (src/tokenizer.cpp:340-368):
    full rescan per merge, strictly-best score, earliest pair on ties."""
    tokens = list(tokens)
    while True:
        best_score, best_id, best_idx = -1e10, -1, -1
        for j in range(len(tokens) - 1):
            a, b = tokens[j], tokens[j + 1]
            if a >= tok.vocab_size or b >= tok.vocab_size:
                continue
            merged = tok._regular.get(tok.vocab[a] + tok.vocab[b])
            if merged is not None and tok.scores[merged] > best_score:
                best_score, best_id, best_idx = tok.scores[merged], merged, j
        if best_idx == -1:
            break
        tokens[best_idx : best_idx + 2] = [best_id]
    return tokens


def test_heap_merge_matches_reference_rescan(tok):
    import random

    rng = random.Random(0)
    corpus = "hello world wo rl d helhello   worldworld hel lo "
    for trial in range(50):
        n = rng.randint(0, 60)
        text = "".join(rng.choice(corpus) for _ in range(n))
        seed = []
        buf = b""
        for byte in text.encode():
            buf += bytes([byte])
            tid = tok._regular.get(buf)
            if tid is not None:
                seed.append(tid)
                buf = b""
        assert not buf
        assert tok._merge(seed) == _merge_reference(tok, seed), (trial, text)


def test_long_prompt_encode_is_fast(tok):
    """100k-char admission must not stall the scheduler thread (VERDICT
    round-3 Weak #7): the heap merge is O(n log n), so a generous wall
    bound catches any regression back to quadratic (which takes minutes)."""
    import time

    text = "hello world " * 8500  # ~100k chars
    t0 = time.perf_counter()
    ids = tok.encode(text, add_bos=False, add_special_tokens=True)
    elapsed = time.perf_counter() - t0
    assert "".join(tok.vocab[i].decode() for i in ids) == text
    assert elapsed < 5.0, f"100k-char encode took {elapsed:.1f}s"
