"""RMS norm.

Matches the reference's two-op split semantics (OP_INV_RMS computes
1/sqrt(mean(x^2)+eps) per row in f32, OP_RMS_NORM multiplies by the weight;
src/nn/nn-cpu-ops.cpp:105-180) as a single fused op — XLA fuses the reduction
and the scale into one VPU pass anyway.
"""

from __future__ import annotations

import jax.numpy as jnp
import jax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """x: [..., dim]; weight: [dim]. Reduction in float32 regardless of x dtype."""
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * inv * weight.astype(jnp.float32)).astype(x.dtype)
