"""Crash-durable request journal: append-only, CRC-framed, torn-tail
tolerant.

PR 8 made a serving-process death CONTAINED (supervised loop, breaker,
watchdog — the watchdog even dies on purpose, ``os._exit(17)``, on a pod
stall); this module makes it RECOVERABLE. Every admitted request is
journaled with everything deterministic replay needs — prompt tokens,
sampler params including the RESOLVED seed (an unseeded request draws OS
entropy at admission; the journal records the draw, so a replay samples
the identical ``fold_in(seed, pos)`` stream — the determinism class
``tests/test_sampler_parity.py`` pins) — plus periodic per-request
progress watermarks (tokens already DELIVERED to the client transport)
and a finish record. After a crash, ``read_journal`` reconstructs the
in-flight set and serving/recovery.py regenerates each incomplete
request from its prompt with the same seed, fast-forwarding emission
through the watermark (serving/resume.py), so the resumed stream is
byte-identical to the uninterrupted one.

On-disk format (binary, little-endian)::

    magic   := b"DLJRNL01"                     (8 bytes, file head)
    record  := u32 crc32(payload) | u32 len(payload) | payload
    payload := compact JSON, {"k": "admit" | "progress" | "finish", ...}

A reader stops at the first short or CRC-failing frame — a crash mid
``write()`` leaves a torn tail, never a corrupt replay (the torn records
were not yet durable, so the requests they describe simply resume from
an earlier watermark, or re-run in full). Unknown record kinds are
skipped, not fatal: old binaries read new journals.

Writes go through a BACKGROUND writer thread: ``record_admit`` /
``note_progress`` / ``record_finish`` only append to an in-memory queue
under the journal lock (dlint guarded-by discipline); the writer drains
batches and does file I/O outside any lock, so the serving loop never
blocks on the disk. A write failure (ENOSPC, or the ``journal.write``
fault point) is counted and contained — journaling degrades, serving
never stops. Flag-gated: ``--journal-path``, off by default.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from collections import OrderedDict
from dataclasses import dataclass, field

from ..lockcheck import make_lock
from ..utils import faults

MAGIC = b"DLJRNL01"
_FRAME = struct.Struct("<II")  # crc32(payload), len(payload)
# a frame longer than this is torn/garbage, not a real record (admit
# records are ~prompt-sized; far below this)
MAX_RECORD_BYTES = 16 << 20
# bound on queued-but-unwritten records: the admission queue is itself
# bounded (--max-queue), so this only trips when the disk stalls for a
# long time — then records drop (counted) rather than growing the heap
MAX_PENDING = 65536


def admit_record(*, request_id: int, prompt: str, tokens: list[int],
                 max_tokens: int, temperature: float, topp: float,
                 seed: int, stop: list[str], add_bos: bool,
                 add_special_tokens: bool, user: str | None, priority: int,
                 queue_timeout_s: float | None, budget_s: float | None,
                 stream: bool, kind: str | None = None,
                 response_format: dict | None = None,
                 trace: str | None = None) -> dict:
    """THE admit wire record — one field-mapping site shared by
    :meth:`RequestJournal.record_admit` (the on-disk journal) and the
    scheduler's live-session mirror (``export_session``, the fleet
    migration ticket a router hands to another replica), so the two
    encodings provably cannot drift. Everything a deterministic replay
    needs, with the RESOLVED seed."""
    return {
        "k": "admit", "id": int(request_id), "prompt": prompt,
        "tokens": [int(t) for t in tokens],
        "max_tokens": int(max_tokens), "temp": float(temperature),
        "topp": float(topp), "seed": int(seed),
        "stop": list(stop), "add_bos": bool(add_bos),
        # user None stays null: an anonymous request must come back
        # from recovery anonymous, not as a QoS fair-share user
        # literally named "None"
        "add_special": bool(add_special_tokens),
        "user": None if user is None else str(user),
        "prio": int(priority), "queue_timeout_s": queue_timeout_s,
        "budget_s": budget_s, "stream": bool(stream), "kind": kind,
        # structured output (grammar/): the response_format the automaton
        # recompiles from on replay/migration — with the journaled seed it
        # makes a constrained stream deterministic from (prompt, seed,
        # schema). None for unconstrained requests (old journals decode
        # with the same default).
        "response_format": response_format,
        # fleet trace context (telemetry/tracectx.py, "tid-sid" wire
        # form): because this single encoding site also feeds the
        # migration ticket, a recovered OR migrated stream rejoins its
        # original trace instead of starting a fresh one
        "trace": None if trace is None else str(trace),
    }


def entry_from_admit_record(rec: dict) -> "JournalEntry":
    """Materialize one admit wire record (as :func:`admit_record` /
    ``record_admit`` encode it) back into a :class:`JournalEntry` —
    the decode half of the fleet migration ticket: a replica's
    ``/admin/migrate`` endpoint feeds the result straight into
    ``scheduler.build_recovered_request``, the same path crash recovery
    replays through. Runs the SAME fold ``read_journal`` uses
    (:meth:`JournalImage.apply`), so the two decoders cannot drift; an
    optional ``watermark`` field rides along (tokens the source replica
    had delivered — informational: resumption is by ``Last-Event-ID``,
    never by watermark skip). Raises ``ValueError`` on a malformed
    record."""
    if rec.get("k", "admit") != "admit":
        raise ValueError(f"not an admit record (k={rec.get('k')!r})")
    image = JournalImage()
    try:
        image.apply({**rec, "k": "admit"})
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed admit record: {e}") from e
    if not image.entries:
        raise ValueError("admit record carried no request id")
    entry = next(iter(image.entries.values()))
    try:
        entry.watermark = max(0, int(rec.get("watermark", 0) or 0))
    except (TypeError, ValueError) as e:
        raise ValueError(f"malformed watermark: {e}") from e
    return entry


@dataclass
class JournalEntry:
    """One request's journaled state after a sequential replay of the
    file: the admit fields plus the folded-in progress/finish records."""

    request_id: int
    prompt: str = ""
    tokens: list[int] = field(default_factory=list)
    max_tokens: int = 128
    temperature: float = 0.0
    topp: float = 0.9
    seed: int = 0  # RESOLVED lane seed (never None: replay must reproduce it)
    stop: list[str] = field(default_factory=list)
    add_bos: bool = True
    add_special_tokens: bool = True
    user: str | None = None
    priority: int = 1
    queue_timeout_s: float | None = None
    budget_s: float | None = None
    stream: bool = False
    kind: str | None = None  # "chat" | "completion" | None (CLI/bench)
    response_format: dict | None = None  # structured output (grammar/)
    trace: str | None = None  # fleet trace context, "tid-sid" wire form
    watermark: int = 0  # tokens already delivered to the client transport
    finished: bool = False
    finish_reason: str | None = None
    phases: dict | None = None  # latency attribution off the finish record


class JournalImage:
    """The journal file, replayed: per-request entries in admit order,
    plus the read-side accounting (record count, torn tail)."""

    def __init__(self):
        self.entries: "OrderedDict[int, JournalEntry]" = OrderedDict()
        self.records = 0
        self.torn = False  # file ended mid-frame / CRC-failed (crash tail)
        self.skipped = 0  # unknown record kinds (forward compat)

    def incomplete(self) -> list[JournalEntry]:
        """Entries with no finish record, in admit order — the set a
        recovery replay re-admits."""
        return [e for e in self.entries.values() if not e.finished]

    def apply(self, rec: dict) -> None:
        kind = rec.get("k")
        if kind == "admit":
            rid = int(rec["id"])
            prev = self.entries.pop(rid, None)
            e = JournalEntry(
                request_id=rid,
                prompt=str(rec.get("prompt", "")),
                tokens=[int(t) for t in rec.get("tokens", [])],
                max_tokens=int(rec.get("max_tokens", 128)),
                temperature=float(rec.get("temp", 0.0)),
                topp=float(rec.get("topp", 0.9)),
                seed=int(rec.get("seed", 0)),
                stop=[str(s) for s in rec.get("stop", [])],
                add_bos=bool(rec.get("add_bos", True)),
                add_special_tokens=bool(rec.get("add_special", True)),
                user=(None if rec.get("user") is None
                      else str(rec.get("user"))),
                priority=int(rec.get("prio", 1)),
                queue_timeout_s=rec.get("queue_timeout_s"),
                budget_s=rec.get("budget_s"),
                stream=bool(rec.get("stream", False)),
                kind=rec.get("kind"),
                response_format=(
                    dict(rec["response_format"])
                    if isinstance(rec.get("response_format"), dict)
                    else None
                ),
                trace=(
                    str(rec["trace"])
                    if isinstance(rec.get("trace"), str)
                    else None
                ),
            )
            if prev is not None:
                # a recovered request re-journals on re-admission: its
                # progress watermark is ABSOLUTE (token index from the
                # stream's start), so delivery state carries across
                # crash generations
                e.watermark = prev.watermark
            self.entries[rid] = e
        elif kind == "progress":
            e = self.entries.get(int(rec.get("id", -1)))
            if e is not None:
                e.watermark = max(e.watermark, int(rec.get("n", 0)))
        elif kind == "finish":
            e = self.entries.get(int(rec.get("id", -1)))
            if e is not None:
                e.finished = True
                e.finish_reason = rec.get("reason")
                if isinstance(rec.get("phases"), dict):
                    e.phases = dict(rec["phases"])
        else:
            self.skipped += 1


def read_journal(path: str) -> JournalImage:
    """Sequentially replay a journal file into a :class:`JournalImage`.
    Tolerates the crash shapes by construction: a missing file is an
    empty image; a torn tail (short frame, short payload, CRC mismatch,
    absurd length) stops the replay at the last durable record."""
    image = JournalImage()
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return image
    with f:
        if f.read(len(MAGIC)) != MAGIC:
            image.torn = True  # not a journal (or a torn first write)
            return image
        while True:
            head = f.read(_FRAME.size)
            if len(head) < _FRAME.size:
                image.torn = len(head) > 0
                return image
            crc, n = _FRAME.unpack(head)
            if n > MAX_RECORD_BYTES:
                image.torn = True
                return image
            payload = f.read(n)
            if len(payload) < n or zlib.crc32(payload) != crc:
                image.torn = True
                return image
            try:
                rec = json.loads(payload)
            except ValueError:
                image.torn = True  # CRC passed but not JSON: foreign data
                return image
            image.records += 1
            image.apply(rec)


def _durable_end(path: str) -> int | None:
    """Byte offset just past the last durable frame, or ``None`` when
    the file does not start with the journal magic. The writer truncates
    a reopened journal here BEFORE appending: frames appended after a
    crash-torn tail would sit behind the tear, where no reader (which
    stops at the first bad frame) could ever see them."""
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            return None
        off = len(MAGIC)
        while True:
            head = f.read(_FRAME.size)
            if len(head) < _FRAME.size:
                return off
            crc, n = _FRAME.unpack(head)
            if n > MAX_RECORD_BYTES:
                return off
            payload = f.read(n)
            if len(payload) < n or zlib.crc32(payload) != crc:
                return off
            off += _FRAME.size + n


class RequestJournal:
    """Append-only journal with a background writer thread.

    The record_* methods enqueue under ``_lock`` and return immediately;
    the writer drains batches, frames them (CRC32 + length prefix) and
    writes outside any lock. ``flush()`` blocks until everything
    enqueued so far is on disk (fsync'd when ``fsync=True``); ``close()``
    flushes and joins the writer. Write failures are contained: counted
    in ``journal_errors`` (surfaced on ``/stats`` via the scheduler),
    the failing batch is dropped, serving continues.
    """

    # dlint guarded-by declaration (analysis/lock_check.py): the pending
    # queue and all journal counters move only under _lock — directly or
    # via the _cv Condition built over it (entering either IS holding the
    # lock) — record_* run on scheduler/HTTP threads, the drain on the
    # writer thread.
    _dlint_guarded_by = {
        ("_lock", "_cv"): (
            "_j_pending", "_j_seq", "_j_written_seq", "_j_closed",
            "_j_records", "_j_bytes", "_j_errors", "_j_dropped",
            "_j_progress_mark",
        ),
    }

    # dlint resource-lifecycle declaration (analysis/resourcemodel.py):
    # ``record_admit`` opens a per-request progress mark that only
    # ``record_finish`` closes — an admit whose finish record lost an
    # exit path grows ``_j_progress_mark`` forever (the PR 10 leak this
    # mark map originally shipped with). Checked by resource-balance;
    # witnessed via ``journal_open_marks`` at scheduler stop
    # (analysis/leakcheck.py).
    _dlint_acquires = {"journal-mark": ("record_admit",)}
    _dlint_releases = {"journal-mark": ("record_finish",)}

    def __init__(self, path: str, progress_every: int = 8,
                 fsync: bool = True):
        if progress_every < 1:
            raise ValueError("progress_every must be >= 1")
        self.path = path
        self.progress_every = int(progress_every)
        self.fsync = bool(fsync)
        self._lock = make_lock("RequestJournal._lock")
        self._cv = threading.Condition(self._lock)
        self._j_pending: list[dict] = []
        self._j_seq = 0  # records ever enqueued
        self._j_written_seq = 0  # records written (or dropped on error)
        self._j_closed = False
        self._j_records = 0  # records durably written
        self._j_bytes = 0
        self._j_errors = 0  # contained write failures (batches lost)
        self._j_dropped = 0  # records shed at MAX_PENDING
        # per-request last-journaled watermark (rate-limits progress
        # records to one per `progress_every` delivered tokens)
        self._j_progress_mark: dict[int, int] = {}
        # open (and stamp) the file up front so a bad path fails the
        # operator at startup, not the writer thread mid-serving
        new = not os.path.exists(path) or os.path.getsize(path) == 0
        if not new:
            end = _durable_end(path)
            if end is None:
                raise ValueError(
                    f"{path} exists but is not a request journal "
                    "(bad magic) — refusing to append"
                )
            if end < os.path.getsize(path):
                # crash-torn tail from the previous generation: cut it
                # off before appending, or every record this process
                # writes lands behind the tear and is unreadable forever
                with open(path, "r+b") as tf:
                    tf.truncate(end)
        self._file = open(path, "ab")
        if new:
            self._file.write(MAGIC)
            self._file.flush()
        self._thread = threading.Thread(
            target=self._writer, name="journal-writer", daemon=True
        )
        self._thread.start()

    # -- producer side (scheduler / HTTP threads) ---------------------------

    def record_admit(self, *, request_id: int, prompt: str,
                     tokens: list[int], max_tokens: int, temperature: float,
                     topp: float, seed: int, stop: list[str], add_bos: bool,
                     add_special_tokens: bool, user: str | None,
                     priority: int,
                     queue_timeout_s: float | None, budget_s: float | None,
                     stream: bool, kind: str | None = None,
                     response_format: dict | None = None,
                     trace: str | None = None) -> None:
        """One admitted request, with the RESOLVED seed — everything a
        deterministic replay needs to regenerate the identical stream."""
        with self._lock:
            # seed the progress mark: note_progress only advances marks
            # that exist, so a pump delivering a tail delta AFTER the
            # finish record popped the mark cannot resurrect the entry
            # (a per-request leak plus a spurious post-finish record)
            self._j_progress_mark.setdefault(int(request_id), 0)
        self._enqueue(admit_record(
            request_id=request_id, prompt=prompt, tokens=tokens,
            max_tokens=max_tokens, temperature=temperature, topp=topp,
            seed=seed, stop=stop, add_bos=add_bos,
            add_special_tokens=add_special_tokens, user=user,
            priority=priority, queue_timeout_s=queue_timeout_s,
            budget_s=budget_s, stream=stream, kind=kind,
            response_format=response_format, trace=trace,
        ))

    def note_progress(self, request_id: int, tokens_delivered: int) -> None:
        """Advance a request's delivery watermark. Called AFTER a delta
        was handed to the client transport (the HTTP pump / resume
        relay). NOTE: "handed to the transport" means written to the
        socket, not received — a crash can strand written deltas in the
        kernel send buffer, so the watermark may sit AHEAD of the
        client's true position. It is a progress/diagnostics floor
        (``recovery_replayed_tokens``), never a license to discard
        replayed deltas on recovery (serving/recovery.py re-buffers from
        0 and lets ``Last-Event-ID`` pick the resume point).
        Rate-limited: one record per ``progress_every`` tokens."""
        with self._lock:
            last = self._j_progress_mark.get(int(request_id))
            if last is None:
                # finished (record_finish popped the mark) or never
                # admitted: late pump deliveries journal nothing
                return
            if tokens_delivered - last < self.progress_every:
                return
            self._j_progress_mark[int(request_id)] = int(tokens_delivered)
        self._enqueue({
            "k": "progress", "id": int(request_id),
            "n": int(tokens_delivered),
        })

    def record_finish(self, request_id: int, reason: str | None,
                      phases: dict | None = None) -> None:
        """The finish record; ``phases`` (when the scheduler hands one)
        is the per-request latency attribution dict — journaled so
        post-mortem analysis of a crashed window has the same phase
        numbers the completion response carried."""
        with self._lock:
            self._j_progress_mark.pop(int(request_id), None)
        rec = {"k": "finish", "id": int(request_id), "reason": reason}
        if phases:
            rec["phases"] = dict(phases)
        self._enqueue(rec)

    def _enqueue(self, rec: dict) -> None:
        with self._cv:
            if self._j_closed:
                self._j_dropped += 1
                return
            if len(self._j_pending) >= MAX_PENDING:
                self._j_dropped += 1
                return
            self._j_pending.append(rec)
            self._j_seq += 1
            self._cv.notify_all()

    # -- writer thread -------------------------------------------------------

    def _writer(self) -> None:
        while True:
            with self._cv:
                while not self._j_pending and not self._j_closed:
                    self._cv.wait(0.5)
                batch = self._j_pending
                self._j_pending = []
                closed = self._j_closed
                if not batch and closed:
                    self._cv.notify_all()
                    return
            n_written, n_bytes, failed = self._write_batch(batch)
            with self._cv:
                self._j_written_seq += len(batch)
                self._j_records += n_written
                self._j_bytes += n_bytes
                if failed:
                    self._j_errors += 1
                self._cv.notify_all()

    def _write_batch(self, batch: list[dict]) -> tuple[int, int, bool]:
        """Frame and write one batch — file I/O outside any lock. A raise
        (real ENOSPC or the ``journal.write`` fault point) is contained:
        the batch is dropped and counted, serving never sees it."""
        buf = bytearray()
        for rec in batch:
            payload = json.dumps(rec, separators=(",", ":")).encode()
            buf += _FRAME.pack(zlib.crc32(payload), len(payload))
            buf += payload
        try:
            faults.fire("journal.write")
            self._file.write(buf)
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
        except Exception:  # noqa: BLE001 — journaling degrades, never kills
            return 0, 0, True
        return len(batch), len(buf), False

    # -- lifecycle -----------------------------------------------------------

    def flush(self, timeout: float | None = 10.0) -> bool:
        """Block until every record enqueued before this call is written
        (or dropped by a contained error). True when the barrier was
        reached within ``timeout``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            target = self._j_seq
            while self._j_written_seq < target:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True

    def close(self, timeout: float | None = 10.0) -> None:
        """Flush, stop the writer, close the file. Idempotent."""
        with self._cv:
            self._j_closed = True
            self._cv.notify_all()
        self._thread.join(timeout)
        try:
            self._file.close()
        except Exception:  # noqa: BLE001 — shutdown must not throw
            pass

    def stats(self) -> dict:
        """Journal counters for /stats (one lock hold); bridged to
        /metrics as dllama_stats_journal_* gauges plus the delta-fed
        dllama_journal_records_total counter."""
        with self._lock:
            return {
                "journal_records": self._j_records,
                "journal_bytes": self._j_bytes,
                "journal_errors": self._j_errors,
                "journal_dropped": self._j_dropped,
                "journal_pending": len(self._j_pending),
                # admits whose finish record has not landed yet: the
                # leak witness's journal-mark gauge — after a clean
                # scheduler stop every admitted request finished, so a
                # non-zero count is a record_admit whose record_finish
                # lost an exit path (analysis/leakcheck.py)
                "journal_open_marks": len(self._j_progress_mark),
            }
