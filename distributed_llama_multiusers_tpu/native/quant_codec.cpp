// Native Q40/Q80 block codec — the C++ runtime component backing the host
// quantization path (counterpart of the reference's src/nn/nn-quants.cpp,
// re-implemented: same on-disk format, fresh code).
//
// Semantics are bit-exact with quants/codec.py:
//   Q40: 32-elt block, fp16 scale d = signed_absmax / -8,
//        q = clip(trunc(x/d + 8.5), 0, 15), low nibbles = elts [0,16)
//   Q80: 32-elt block, fp16 scale d = absmax / 127,
//        q = round(x/d)  (ties-away "runtime" or ties-even "converter")
// fp16 conversion is IEEE round-to-nearest-even.
//
// Exposed as a C ABI for ctypes; all entry points release the GIL by
// construction (pure C, no Python API). Multi-threaded over blocks.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <algorithm>
#include <thread>
#include <vector>
#include <string>
#include <unordered_map>
#include <queue>

namespace {

constexpr int kBlock = 32;
constexpr int kQ40Bytes = 18; // 2B f16 scale + 16 nibble bytes
constexpr int kQ80Bytes = 34; // 2B f16 scale + 32 int8

inline uint16_t f32_to_f16(float f) {
    uint32_t x;
    std::memcpy(&x, &f, 4);
    const uint32_t sign = (x >> 16) & 0x8000u;
    uint32_t mant = x & 0x007FFFFFu;
    const uint32_t exp_bits = (x >> 23) & 0xFFu;
    const int32_t exp = (int32_t)exp_bits - 127 + 15;
    if (exp_bits == 0xFF) // inf / nan
        return (uint16_t)(sign | 0x7C00u | (mant ? 0x200u : 0u));
    if (exp >= 31) // overflow -> inf
        return (uint16_t)(sign | 0x7C00u);
    if (exp <= 0) {
        if (exp < -10)
            return (uint16_t)sign;
        mant |= 0x00800000u;
        const uint32_t shift = (uint32_t)(14 - exp);
        uint32_t half = mant >> shift;
        const uint32_t rem = mant & ((1u << shift) - 1u);
        const uint32_t halfway = 1u << (shift - 1);
        if (rem > halfway || (rem == halfway && (half & 1u)))
            half++;
        return (uint16_t)(sign | half);
    }
    uint32_t out = sign | ((uint32_t)exp << 10) | (mant >> 13);
    const uint32_t rem = mant & 0x1FFFu;
    if (rem > 0x1000u || (rem == 0x1000u && (out & 1u)))
        out++;
    return (uint16_t)out;
}

inline float f16_to_f32(uint16_t h) {
    const uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
    uint32_t exp = (h >> 10) & 0x1Fu;
    uint32_t mant = h & 0x3FFu;
    uint32_t x;
    if (exp == 0) {
        if (mant == 0) {
            x = sign;
        } else { // subnormal
            exp = 127 - 15 + 1;
            while (!(mant & 0x400u)) {
                mant <<= 1;
                exp--;
            }
            mant &= 0x3FFu;
            x = sign | (exp << 23) | (mant << 13);
        }
    } else if (exp == 31) {
        x = sign | 0x7F800000u | (mant << 13);
    } else {
        x = sign | ((exp - 15 + 127) << 23) | (mant << 13);
    }
    float f;
    std::memcpy(&f, &x, 4);
    return f;
}

template <typename Fn>
void parallel_blocks(int64_t n_blocks, int n_threads, Fn fn) {
    if (n_threads <= 1 || n_blocks < 1024) {
        fn(0, n_blocks);
        return;
    }
    std::vector<std::thread> threads;
    const int64_t per = (n_blocks + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; t++) {
        const int64_t lo = t * per;
        const int64_t hi = std::min(n_blocks, lo + per);
        if (lo >= hi)
            break;
        threads.emplace_back([=] { fn(lo, hi); });
    }
    for (auto &th : threads)
        th.join();
}

} // namespace

extern "C" {

// x: n_blocks*32 floats -> out: n_blocks*18 bytes
void dlq_q40_quantize(const float *x, uint8_t *out, int64_t n_blocks, int n_threads) {
    parallel_blocks(n_blocks, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t b = lo; b < hi; b++) {
            const float *p = x + b * kBlock;
            uint8_t *o = out + b * kQ40Bytes;
            // tie-break must match the numpy codec (and converter/writer.py):
            // when -min == max, the POSITIVE extreme wins
            float gmin = p[0], gmax = p[0];
            for (int j = 1; j < kBlock; j++) {
                gmin = std::min(gmin, p[j]);
                gmax = std::max(gmax, p[j]);
            }
            const float maxv = (-gmin > gmax) ? gmin : gmax;
            const float d = maxv / -8.0f;
            const float id = d != 0.0f ? 1.0f / d : 0.0f;
            const uint16_t d16 = f32_to_f16(d);
            std::memcpy(o, &d16, 2);
            for (int j = 0; j < kBlock / 2; j++) {
                float q0 = p[j] * id + 8.5f;
                float q1 = p[j + kBlock / 2] * id + 8.5f;
                int i0 = (int)std::min(std::max(q0, 0.0f), 15.0f);
                int i1 = (int)std::min(std::max(q1, 0.0f), 15.0f);
                o[2 + j] = (uint8_t)((i0 & 0xF) | ((i1 & 0xF) << 4));
            }
        }
    });
}

void dlq_q40_dequantize(const uint8_t *in, float *out, int64_t n_blocks, int n_threads) {
    parallel_blocks(n_blocks, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t b = lo; b < hi; b++) {
            const uint8_t *p = in + b * kQ40Bytes;
            float *o = out + b * kBlock;
            uint16_t d16;
            std::memcpy(&d16, p, 2);
            const float d = f16_to_f32(d16);
            for (int j = 0; j < kBlock / 2; j++) {
                const uint8_t byte = p[2 + j];
                o[j] = (float)((int)(byte & 0x0F) - 8) * d;
                o[j + kBlock / 2] = (float)((int)(byte >> 4) - 8) * d;
            }
        }
    });
}

// planar decode for on-device use: int8 values [-8,7]+..., f32 scales
void dlq_q40_to_planar(const uint8_t *in, int8_t *values, float *scales, int64_t n_blocks, int n_threads) {
    parallel_blocks(n_blocks, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t b = lo; b < hi; b++) {
            const uint8_t *p = in + b * kQ40Bytes;
            int8_t *v = values + b * kBlock;
            uint16_t d16;
            std::memcpy(&d16, p, 2);
            scales[b] = f16_to_f32(d16);
            for (int j = 0; j < kBlock / 2; j++) {
                const uint8_t byte = p[2 + j];
                v[j] = (int8_t)((int)(byte & 0x0F) - 8);
                v[j + kBlock / 2] = (int8_t)((int)(byte >> 4) - 8);
            }
        }
    });
}

// ties_even != 0 -> converter mode (rint, round-half-even);
// ties_even == 0 -> runtime mode (roundf, half away from zero)
void dlq_q80_quantize(const float *x, uint8_t *out, int64_t n_blocks, int ties_even, int n_threads) {
    parallel_blocks(n_blocks, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t b = lo; b < hi; b++) {
            const float *p = x + b * kBlock;
            uint8_t *o = out + b * kQ80Bytes;
            float amax = 0.0f;
            for (int j = 0; j < kBlock; j++)
                amax = std::max(amax, std::fabs(p[j]));
            const float d = amax / 127.0f;
            const float id = d != 0.0f ? 1.0f / d : 0.0f;
            const uint16_t d16 = f32_to_f16(d);
            std::memcpy(o, &d16, 2);
            int8_t *q = (int8_t *)(o + 2);
            if (ties_even) {
                for (int j = 0; j < kBlock; j++)
                    q[j] = (int8_t)std::rint(p[j] * id);
            } else {
                for (int j = 0; j < kBlock; j++)
                    q[j] = (int8_t)std::roundf(p[j] * id);
            }
        }
    });
}

void dlq_q80_dequantize(const uint8_t *in, float *out, int64_t n_blocks, int n_threads) {
    parallel_blocks(n_blocks, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t b = lo; b < hi; b++) {
            const uint8_t *p = in + b * kQ80Bytes;
            float *o = out + b * kBlock;
            uint16_t d16;
            std::memcpy(&d16, p, 2);
            const float d = f16_to_f32(d16);
            const int8_t *q = (const int8_t *)(p + 2);
            for (int j = 0; j < kBlock; j++)
                o[j] = (float)q[j] * d;
        }
    });
}

// f16 <-> f32 array converters (counterpart of convertF16toF32Impl et al.)
void dlq_f16_to_f32(const uint16_t *in, float *out, int64_t n, int n_threads) {
    parallel_blocks(n, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; i++)
            out[i] = f16_to_f32(in[i]);
    });
}

void dlq_f32_to_f16(const float *in, uint16_t *out, int64_t n, int n_threads) {
    parallel_blocks(n, n_threads, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; i++)
            out[i] = f32_to_f16(in[i]);
    });
}

int dlq_abi_version(void) { return 2; }

} // extern "C"

// ---------------------------------------------------------------------------
// BPE pair-merge — the tokenizer's encode hot path (counterpart of the
// reference's iterative best-score merge, src/tokenizer.cpp:340-368). Same
// heap-over-candidate-pairs algorithm as tokenizer.Tokenizer._merge, with
// the identical order contract (strictly-best score, EARLIEST pair on
// ties), so native and Python merges are token-identical; the Python side
// A/B-checks this in tests/test_native.py. Long prompts (the long-context
// serving workload) spend their admission time here.

namespace {

struct BpeCtx {
    std::vector<std::string> vocab;   // id -> bytes, FULL vocab (specials too)
    std::vector<float> scores;        // full vocab
    // regular-vocab bytes -> id; built with emplace over ascending ids so
    // duplicates keep the FIRST id, matching dict.setdefault in Python
    std::unordered_map<std::string, int32_t> regular;
    // specials grouped by first byte, id order within a group — the scan
    // takes the first prefix match, like Tokenizer._find_special_at
    std::vector<std::vector<std::pair<int32_t, const std::string *>>> specials_by_first;
};

// the iterative best-score pair merge over a linked list + candidate heap;
// mutates ids in place and returns the merged length (algorithm contract
// documented at dllama_bpe_merge below)
int32_t bpe_merge_core(BpeCtx *ctx, std::vector<int32_t> &ids) {
    const int32_t V = (int32_t)ctx->vocab.size();
    const int32_t n = (int32_t)ids.size();
    if (n < 2) return n;
    std::vector<int32_t> nxt(n), prv(n);
    std::vector<char> alive(n, 1);
    for (int32_t j = 0; j < n; j++) { nxt[j] = j + 1; prv[j] = j - 1; }

    struct Cand { float neg_score; int32_t j, merged, a, b; };
    auto cmp = [](const Cand &x, const Cand &y) {
        if (x.neg_score != y.neg_score) return x.neg_score > y.neg_score;
        return x.j > y.j;
    };
    std::priority_queue<Cand, std::vector<Cand>, decltype(cmp)> heap(cmp);
    std::string key;
    auto push = [&](int32_t j) {
        const int32_t k = nxt[j];
        if (k >= n) return;
        const int32_t a = ids[j], b = ids[k];
        if (a < 0 || b < 0 || a >= V || b >= V) return;
        key.assign(ctx->vocab[a]);
        key.append(ctx->vocab[b]);
        auto it = ctx->regular.find(key);
        if (it == ctx->regular.end()) return;
        const int32_t m = it->second;
        if ((double)ctx->scores[m] > -1e10)  // double, like Python
            heap.push({-ctx->scores[m], j, m, a, b});
    };
    for (int32_t j = 0; j + 1 < n; j++) push(j);
    while (!heap.empty()) {
        const Cand c = heap.top();
        heap.pop();
        const int32_t j = c.j, k = nxt[j];
        // stale entry: one side merged away or re-merged since the push
        if (!alive[j] || k >= n || ids[j] != c.a || ids[k] != c.b) continue;
        ids[j] = c.merged;
        alive[k] = 0;
        nxt[j] = nxt[k];
        if (nxt[k] < n) prv[nxt[k]] = j;
        if (prv[j] >= 0) push(prv[j]);
        push(j);
    }
    int32_t m = 0;
    for (int32_t j = 0; j < n; j++)
        if (alive[j]) ids[m++] = ids[j];
    ids.resize(m);
    return m;
}

} // namespace

extern "C" {

void *dllama_bpe_create(const uint8_t *vocab_bytes, const int64_t *offsets,
                        int32_t n_vocab, int32_t n_regular,
                        const float *scores) {
    auto *ctx = new BpeCtx();
    ctx->vocab.reserve(n_vocab);
    ctx->scores.assign(scores, scores + n_vocab);
    for (int32_t i = 0; i < n_vocab; i++)
        ctx->vocab.emplace_back((const char *)vocab_bytes + offsets[i],
                                (size_t)(offsets[i + 1] - offsets[i]));
    ctx->regular.reserve((size_t)n_regular * 2);
    for (int32_t i = 0; i < n_regular; i++)
        ctx->regular.emplace(ctx->vocab[i], i);
    ctx->specials_by_first.resize(256);
    for (int32_t i = n_regular; i < n_vocab; i++)
        if (!ctx->vocab[i].empty())
            ctx->specials_by_first[(uint8_t)ctx->vocab[i][0]].emplace_back(
                i, &ctx->vocab[i]);
    return ctx;
}

void dllama_bpe_destroy(void *ctx) { delete (BpeCtx *)ctx; }

int32_t dllama_bpe_merge(void *vctx, const int32_t *ids_in, int32_t n,
                         int32_t *out) {
    auto *ctx = (BpeCtx *)vctx;
    std::vector<int32_t> ids(ids_in, ids_in + n);
    const int32_t m = bpe_merge_core(ctx, ids);
    std::copy(ids.begin(), ids.end(), out);
    return m;
}

// Full encode: greedy special-token scan + byte-buffer seed + merge, one
// call per prompt (counterpart of Tokenizer.encode's scan loop +
// src/tokenizer.cpp:301-380). bos >= 0 is prepended BEFORE the merge, as
// in Python where the BOS participates in pair merging. Returns the token
// count, or -(byte_pos+1) when a buffer is untokenizable — the caller
// falls back to the Python encoder, which raises the exact error.
int32_t dllama_bpe_encode(void *vctx, const uint8_t *text, int64_t n,
                          int32_t bos, int add_special, int32_t *out) {
    auto *ctx = (BpeCtx *)vctx;
    std::vector<int32_t> toks;
    toks.reserve((size_t)n + 1);
    if (bos >= 0) toks.push_back(bos);
    std::string buf;
    int64_t i = 0;
    while (i < n) {
        if (add_special) {
            int32_t special = -1;
            for (const auto &cand : ctx->specials_by_first[text[i]]) {
                const std::string &piece = *cand.second;
                if ((int64_t)piece.size() <= n - i &&
                    std::memcmp(piece.data(), text + i, piece.size()) == 0) {
                    special = cand.first;
                    break;
                }
            }
            if (special >= 0) {
                if (!buf.empty()) return (int32_t)(-(i + 1));
                toks.push_back(special);
                i += (int64_t)ctx->vocab[special].size();
                continue;
            }
        }
        buf.push_back((char)text[i]);
        i++;
        auto it = ctx->regular.find(buf);
        if (it != ctx->regular.end()) {
            toks.push_back(it->second);
            buf.clear();
        }
    }
    if (!buf.empty()) return (int32_t)(-(n + 1));
    const int32_t m = bpe_merge_core(ctx, toks);
    std::copy(toks.begin(), toks.end(), out);
    return m;
}

} // extern "C"
