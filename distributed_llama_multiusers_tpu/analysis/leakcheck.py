"""Runtime resource-leak witness (``DLLAMA_LEAKCHECK=1``).

The static half of dlint v5 (``resourcemodel.py`` + the
``resource-balance`` / ``device-affinity`` checks) proves the SOURCE
pairs every acquire with a release; this module proves the PROCESS did —
the jitcheck/lockcheck pattern applied to resource lifecycles. Owners of
lifecycle state call :func:`check_drained` at their natural drain points
with their AUTHORITATIVE live counts (no shadow counters to drift):

- ``ContinuousBatchingScheduler.stop()`` — after the loop thread joins
  and ``_resolve_exit`` has settled every lane: lane-held KV pages
  (``pool_pages_in_use``), live session-mirror records, open journal
  progress marks, and pending device ops must all be zero;
- ``StreamRegistry.close()`` — entries whose request future never
  resolved are orphans nothing can ever reap (the PR 10 shed-path leak
  class, mechanized).

Every call updates the process-wide ``resources_live{kind}`` gauge
snapshot and — when something is still held — bumps
``resource_leaks_total``; both surface on ``/stats`` and bridge to
``/metrics`` (telemetry/hub.py). With the witness ENABLED
(``DLLAMA_LEAKCHECK=1`` or :func:`force`) a non-zero count additionally
raises :class:`ResourceLeak` out of the drain call — a stack trace at
the stop that stranded the resource, instead of a pool that quietly
shrinks across a soak test. Counting is always on (one dict merge per
drain — drains are rare); only the raise is opt-in, the witness family's
zero-production-overhead contract. Pure stdlib; bench serving phases
assert ``leaked_resources == 0`` beside every tok/s number.
"""

from __future__ import annotations

import os

from ..lockcheck import make_lock

ENV_FLAG = "DLLAMA_LEAKCHECK"

_forced: bool | None = None
# guards the witness state below; never held around foreign locks (the
# caller computed its counts before calling in)
_lock = make_lock("leakcheck._lock")
_live: dict[str, int] = {}  # last observed live count per resource kind
_leaks_total = 0  # process lifetime: resources still held at a drain
_checks = 0  # drain points witnessed
_last_leak: dict | None = None  # {"where": ..., "leaked": {...}} diagnostics


class ResourceLeak(AssertionError):
    """A drain point finished with resources still held. AssertionError
    on purpose (the witness-family convention): a leak at drain is a
    failed invariant — an acquire whose release lost an exit path — not
    an operational error to catch and retry."""


def enabled() -> bool:
    """Strict mode: raise at a leaking drain (the counters run
    regardless)."""
    if _forced is not None:
        return _forced
    return os.environ.get(ENV_FLAG, "") not in ("", "0")


def force(value: bool | None, fresh: bool = True) -> None:
    """Test hook: override the env flag (None restores it). ``fresh``
    zeroes the counters so each test starts from a clean witness."""
    global _forced, _leaks_total, _checks, _last_leak
    _forced = value
    if fresh:
        with _lock:
            _leaks_total = 0
            _checks = 0
            _last_leak = None
            _live.clear()


def check_drained(where: str, counts: dict[str, int]) -> int:
    """Witness one drain point: ``counts`` maps resource kind to the
    owner's authoritative live count, which a clean drain leaves at
    zero. Returns the number of leaked resources (and raises it in
    strict mode)."""
    global _leaks_total, _checks, _last_leak
    counts = {str(k): int(v) for k, v in counts.items()}
    leaked = {k: v for k, v in counts.items() if v > 0}
    total = sum(leaked.values())
    with _lock:
        _checks += 1
        _live.update(counts)
        if leaked:
            _leaks_total += total
            _last_leak = {"where": where, "leaked": dict(leaked)}
    if leaked and enabled():
        raise ResourceLeak(
            f"{total} resource(s) still held after {where}: {leaked} — "
            "an acquire lost its release on some exit path (see "
            "docs/LINT.md resource-balance for the pairing vocabulary) "
            f"rather than disabling {ENV_FLAG}."
        )
    return total


def leaks_total() -> int:
    """Process-lifetime count of resources found held at drain points."""
    with _lock:
        return _leaks_total


def live_counts() -> dict[str, int]:
    """Last witnessed live count per kind (a gauge snapshot — updated at
    every drain point, including clean ones)."""
    with _lock:
        return dict(_live)


def last_leak() -> dict | None:
    with _lock:
        return dict(_last_leak) if _last_leak is not None else None


def stats() -> dict:
    """The /stats surface (server/http.py merges this; telemetry/hub.py
    bridges ``resources_live`` as a labelled gauge and delta-feeds
    ``resource_leaks_total`` into its native counter)."""
    with _lock:
        return {
            "resource_leaks_total": _leaks_total,
            "resource_drain_checks": _checks,
            "resources_live": dict(_live),
        }
