"""The prefill-worker contract + hand-off orchestration.

A prefill-role replica is an ordinary serving replica (``ApiServer`` with
``role="prefill"``) — what makes it a prefill WORKER is how the router
drives it: a long-classified request is forwarded whole, the replica runs
its normal bounded-chunk prefill (the scheduler's chunked admission — no
new device programs), and the FIRST streamed delta is the proof that
prefill completed and the prompt's full blocks are committed to the
replica's paged pool (``_paged_commit`` registers them incrementally as
the chunks land). At that point :func:`hand_off` moves the session to a
decode replica:

1. fetch the migration ticket (``GET /admin/session/<id>`` — PR 12's
   admit record: prompt tokens, RESOLVED seed, params, watermark);
2. fetch the KV-page bundle (``GET /admin/kvpages/<id>``,
   :mod:`.kvtransfer`'s integrity-hashed export);
3. push the bundle to the decode replica (``POST /admin/kvimport`` —
   verify + adopt + import, refcount-correct);
4. inject the ticket (``POST /admin/migrate`` — deterministic replay;
   the decode replica's admission finds the adopted prefix in its tree
   and refcount-shares it, so the "re-prefill" is tail-only);
5. reattach the stream (``GET /v1/stream/<id>`` from event 0 — the
   router's ``skip_chars`` dedup makes the client stream char-exact
   across the hand-off).

The prefill replica keeps decoding (and streaming to the client) for the
whole transfer window, so a hand-off that aborts at ANY step degrades to
the monolithic path by doing nothing: the router keeps pumping the
original stream. That is why every failure here is the typed
:class:`HandoffAborted`, never a hung stream — the caller's except arm
IS the fallback.

Pure stdlib; ``fleet.migrate`` is imported lazily inside
:func:`hand_off` (the router imports this module, and the fleet package
re-exports the router — a top-level import would cycle).
"""

from __future__ import annotations

import http.client

from .kvtransfer import KVTransferError  # noqa: F401  (re-export surface)

DEFAULT_TIMEOUT_S = 10.0
# the prompt-length routing knob: at/above this many prompt chars a
# request classifies "long" and routes to a prefill-role replica. ~8k
# chars ≈ a couple thousand tokens — the point where one prompt's
# prefill visibly taxes co-resident decode TBT on a shared replica.
DEFAULT_LONG_PROMPT_CHARS = 8000

_TRANSPORT_ERRORS = (OSError, http.client.HTTPException)


class HandoffAborted(RuntimeError):
    """Typed hand-off failure (any step: ticket, pages, import, inject,
    reattach). The session is still live on the prefill replica — the
    router's fallback is to keep the original stream (monolithic path),
    so the client sees continued output, never a hang."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"disagg hand-off aborted ({reason})"
                         + (f": {detail}" if detail else ""))


def prompt_chars(body: dict) -> int:
    """Prompt length in characters for either API shape: completions
    ``prompt`` (str or list of str) or chat ``messages`` content — the
    same text the router's affinity key hashes, counted instead."""
    p = body.get("prompt")
    if isinstance(p, str):
        return len(p)
    if isinstance(p, list):
        return sum(len(x) for x in p if isinstance(x, str))
    msgs = body.get("messages")
    total = 0
    if isinstance(msgs, list):
        for m in msgs:
            c = m.get("content") if isinstance(m, dict) else None
            if isinstance(c, str):
                total += len(c)
    return total


def classify_prompt(body: dict,
                    threshold_chars: int = DEFAULT_LONG_PROMPT_CHARS) -> str:
    """``"long"`` (route to a prefill-role replica) or ``"short"``
    (least-loaded / affinity as today). A non-positive threshold
    disables disagg routing: everything classifies short."""
    if threshold_chars <= 0:
        return "short"
    return "long" if prompt_chars(body) >= threshold_chars else "short"


def fetch_pages(host: str, port: int, request_id: int,
                timeout: float = DEFAULT_TIMEOUT_S,
                trace: str | None = None) -> dict | None:
    """GET the session's KV-page bundle off the prefill replica.
    ``None`` when the replica has nothing to ship (contiguous engine,
    session already finished, or an error reply) — the hand-off then
    degrades to ticket-only migration, which re-prefills on the decode
    replica. Mirrors ``fleet.migrate.fetch_ticket``'s shape."""
    from ..fleet.migrate import _request_json

    try:
        status, body, _ = _request_json(
            host, port, "GET", f"/admin/kvpages/{int(request_id)}",
            timeout=timeout, trace=trace,
        )
    except _TRANSPORT_ERRORS:
        return None
    if status != 200 or not isinstance(body, dict) or "blocks" not in body:
        return None
    return body


def push_pages(host: str, port: int, bundle: dict,
               timeout: float = DEFAULT_TIMEOUT_S,
               trace: str | None = None) -> dict:
    """POST a page bundle to the decode replica's ``/admin/kvimport``.
    Returns the adoption receipt (``{"pages", "fresh", "reused"}``).
    Raises :class:`HandoffAborted` on any non-200 — including the
    destination's typed 429 pool-exhausted shed and 422 integrity
    failures — so the caller's fallback arm fires."""
    from ..fleet.migrate import _request_json

    try:
        status, body, _ = _request_json(
            host, port, "POST", "/admin/kvimport", body=bundle,
            timeout=timeout, trace=trace,
        )
    except _TRANSPORT_ERRORS as e:
        raise HandoffAborted("import_transport",
                             f"{type(e).__name__}: {e}") from e
    if status != 200:
        reason = (body or {}).get("reason", f"http_{status}") \
            if isinstance(body, dict) else f"http_{status}"
        raise HandoffAborted("import_rejected", str(reason))
    return body if isinstance(body, dict) else {}


def hand_off(src_host: str, src_port: int, request_id: int,
             dst_host: str, dst_port: int,
             timeout: float = DEFAULT_TIMEOUT_S,
             read_timeout: float | None = None,
             trace: str | None = None):
    """Move a live session from the prefill replica (``src``) to the
    decode replica (``dst``). Returns ``(conn, resp, new_request_id,
    receipt)`` — the reattached SSE stream on the decode replica (from
    event 0; the caller dedups with its ``chars_out`` watermark) plus
    the page-adoption receipt. Raises :class:`HandoffAborted` on any
    failure; the session is then still live on ``src`` and the caller
    keeps the original stream (the monolithic fallback).

    ``timeout`` bounds every admin exchange; ``read_timeout`` (default:
    same) bounds reads on the reattached stream, which waits on
    generation — callers pass their generation-length bound. ``trace``
    (the request's wire-form fleet trace context) rides every admin hop
    as ``X-DLlama-Trace``; the ticket's own ``trace`` field is what
    re-joins the decode-side session to the original trace."""
    from ..fleet.migrate import (
        MigrationShed,
        fetch_ticket,
        inject_session,
        open_stream,
    )

    ticket = fetch_ticket(src_host, src_port, request_id, timeout=timeout,
                          trace=trace)
    if ticket is None:
        raise HandoffAborted(
            "no_ticket",
            f"request {request_id} has no exportable session on the "
            "prefill replica (not admitted yet, or already finished)",
        )
    bundle = fetch_pages(src_host, src_port, request_id, timeout=timeout,
                         trace=trace)
    receipt = {"pages": 0, "fresh": 0, "reused": 0}
    if bundle is not None and bundle.get("blocks"):
        # pages BEFORE the ticket: adoption must be visible to the
        # decode replica's admission, or the migrated session prefills
        # from scratch and the transfer bought nothing
        receipt = push_pages(dst_host, dst_port, bundle, timeout=timeout,
                             trace=trace)
    try:
        injected = inject_session(dst_host, dst_port, ticket,
                                  timeout=timeout, trace=trace)
    except MigrationShed as e:
        raise HandoffAborted("decode_shed", str(e)) from e
    except _TRANSPORT_ERRORS as e:
        raise HandoffAborted("inject_transport",
                             f"{type(e).__name__}: {e}") from e
    except ValueError as e:
        raise HandoffAborted("inject_rejected", str(e)) from e
    new_rid = int(injected.get("request_id", request_id))
    try:
        conn, resp = open_stream(
            dst_host, dst_port, new_rid, last_event_id=0,
            timeout=timeout if read_timeout is None else read_timeout,
            connect_timeout=timeout,
        )
    except _TRANSPORT_ERRORS as e:
        raise HandoffAborted("reattach_transport",
                             f"{type(e).__name__}: {e}") from e
    except ValueError as e:
        raise HandoffAborted("reattach_rejected", str(e)) from e
    return conn, resp, new_rid, receipt
