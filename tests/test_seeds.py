"""utils/seeds.py: the sanctioned sampler-seed source (dlint `clock`
bans wall-clock seeds; PR 2 replaced the `int(time.time())` seeds in
app/dllama.py and runtime/scheduler.py with this)."""

from distributed_llama_multiusers_tpu.utils.seeds import fresh_seed


def test_fresh_seed_is_32bit_and_nonzero():
    for _ in range(64):
        s = fresh_seed()
        # 0 is the xorshift64* fixed point: the host Sampler would emit
        # token 0 forever
        assert 0 < s <= 0xFFFFFFFF


def test_fresh_seed_varies_across_calls():
    # OS entropy, not a clock tick: a burst of draws must not collide
    # (two requests admitted "at the same time" used to share a seed)
    draws = {fresh_seed() for _ in range(32)}
    assert len(draws) > 16


def test_scheduler_lane_seed_uses_entropy_not_wall_clock(monkeypatch):
    """The regression PR 2 fixed: freeze time.time and assert the lane
    seed path does not depend on it (unseeded requests must not collide
    within a clock tick)."""
    import time

    import distributed_llama_multiusers_tpu.utils.seeds as seeds

    monkeypatch.setattr(time, "time", lambda: 1_700_000_000.0)
    a, b = seeds.fresh_seed(), seeds.fresh_seed()
    assert a != b
