#!/usr/bin/env python
"""Convert original Meta Llama checkpoints (consolidated.*.pth shards) to `.m`.

Usage: python convert-llama.py <modelPath> <weightsFloatType>

Reimplementation of the reference (converter/convert-llama.py): shards are
merged by concatenating along the tensor-parallel split dim of each weight
class; layers are processed in chunks so at most one pass of shard files is
resident. Q/K are NOT permuted: Meta checkpoints are already in interleaved-
rotary layout (the HF permutation is what undoes it; reference behaves the
same way).
"""

from __future__ import annotations

import gc
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_llama_multiusers_tpu.formats.model_file import ArchType, HiddenAct, ModelHeader, RopeType
from distributed_llama_multiusers_tpu.quants.codec import FloatType
from writer import parse_float_type, write_header, write_tensor

# concat dim per tensor suffix: 0 = output-dim sharded, 1 = input-dim sharded,
# None = replicated (take shard 0)
CONCAT_DIM = {
    "tok_embeddings.weight": 1,
    "output.weight": 0,
    "attention.wq.weight": 0,
    "attention.wk.weight": 0,
    "attention.wv.weight": 0,
    "attention.wo.weight": 1,
    "feed_forward.w1.weight": 0,
    "feed_forward.w2.weight": 1,
    "feed_forward.w3.weight": 0,
    "attention_norm.weight": None,
    "ffn_norm.weight": None,
    "norm.weight": None,
}


def merge(shards: list, key: str) -> np.ndarray:
    import torch

    parts = [s[key] for s in shards]
    dim = CONCAT_DIM[key.split(".", 2)[-1] if key.startswith("layers.") else key]
    if dim is None or len(parts) == 1:
        t = parts[0]
    else:
        t = torch.cat([p for p in parts], dim=dim)
    return t.to(torch.float32).numpy()


def convert(folder: str, weight_type: int, out_path: str) -> None:
    import torch

    with open(os.path.join(folder, "params.json")) as f:
        params = json.load(f)
    shard_paths = sorted(
        os.path.join(folder, f) for f in os.listdir(folder) if f.startswith("consolidated.")
    )
    if not shard_paths:
        raise FileNotFoundError("No consolidated.*.pth files found")
    print(f"💿 loading {len(shard_paths)} shard(s)...")
    shards = [torch.load(p, map_location="cpu", weights_only=True) for p in shard_paths]

    dim = params["dim"]
    n_heads = params["n_heads"]
    n_kv_heads = params.get("n_kv_heads", n_heads)
    embed = merge(shards, "tok_embeddings.weight")
    vocab_size = params.get("vocab_size") or embed.shape[0]
    hidden_dim = merge(shards, "layers.0.feed_forward.w1.weight").shape[0]

    header = ModelHeader(
        version=0,
        arch_type=ArchType.LLAMA,
        hidden_act=HiddenAct.SILU,
        dim=dim,
        hidden_dim=hidden_dim,
        n_layers=params["n_layers"],
        n_heads=n_heads,
        n_kv_heads=n_kv_heads,
        weight_type=weight_type,
        seq_len=params.get("max_seq_len", 2048),
        orig_seq_len=params.get("max_seq_len", 2048),
        vocab_size=vocab_size,
        rope_theta=float(params.get("rope_theta", 10000.0)),
    )
    if params.get("use_scaled_rope"):
        header.rope_type = RopeType.LLAMA3_1
        header.rope_scaling_factor = float(params.get("rope_scale_factor", 8.0))
        header.rope_scaling_low_freq_factor = 1.0
        header.rope_scaling_high_freq_factor = 4.0
        header.rope_scaling_orig_max_seq_len = params.get("original_max_position_embeddings", 8192)

    wt = weight_type
    with open(out_path, "wb") as out:
        write_header(out, header)
        write_tensor(out, embed, FloatType.F32)
        del embed
        gc.collect()
        for l in range(header.n_layers):
            pre = f"layers.{l}"
            write_tensor(out, merge(shards, f"{pre}.attention.wq.weight"), wt)
            write_tensor(out, merge(shards, f"{pre}.attention.wk.weight"), wt)
            write_tensor(out, merge(shards, f"{pre}.attention.wv.weight"), wt)
            write_tensor(out, merge(shards, f"{pre}.attention.wo.weight"), wt)
            write_tensor(out, merge(shards, f"{pre}.feed_forward.w1.weight"), wt)
            write_tensor(out, merge(shards, f"{pre}.feed_forward.w2.weight"), wt)
            write_tensor(out, merge(shards, f"{pre}.feed_forward.w3.weight"), wt)
            write_tensor(out, merge(shards, f"{pre}.attention_norm.weight"), FloatType.F32)
            write_tensor(out, merge(shards, f"{pre}.ffn_norm.weight"), FloatType.F32)
        write_tensor(out, merge(shards, "norm.weight"), FloatType.F32)
        write_tensor(out, merge(shards, "output.weight"), wt)
    print(f"✅ {out_path} created successfully")


def main() -> None:
    if len(sys.argv) < 3:
        print("Usage: python convert-llama.py <modelPath> <weightsFloatType>")
        raise SystemExit(1)
    folder = sys.argv[1]
    weight_type = parse_float_type(sys.argv[2])
    name = os.path.basename(os.path.normpath(folder)).lower()
    convert(folder, weight_type, f"dllama_model_{name}_{sys.argv[2]}.m")


if __name__ == "__main__":
    main()
