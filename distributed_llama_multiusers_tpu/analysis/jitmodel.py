"""Device-program (``jax.jit``) surface model, extracted from the AST.

The serving loop's compile-stability family of invariants — *one
compiled program per (family, bucket), compiled only at warmup* — was
enforced by comments and hand review until PR 15: PR 11's review found
by hand that a bare ``jnp.asarray`` table-leaf replacement changed the
compiled programs' input aval and forced a recompile per admission, and
the same PR had to hot-fix a missed warmup (the COW page-copy program
compiled mid-chain on the first divergent-block admission). This module
mechanizes the surface those audits re-derived every time, the way
``protocol_check.extract_protocol`` models the pod wire protocol:

- every ``jax.jit`` site (decorated closure, inline ``jax.jit(...)``
  assignment, immediately-invoked init-time jit, jit-returning factory)
  with its ``donate_argnums`` / ``static_argnames``;
- the **step families**: ``self.<attr>`` bindings of those sites on the
  engine class (``_decode_fn``, ``_copy_page_fn``, ``_sample_one``, the
  ``_decode_multi_fns`` factory dict, …);
- the **dispatchers**: public engine methods that call a family
  (through direct attribute calls, local aliases, conditional
  expressions, and ``fn(*operands)`` tuple expansion), plus whether the
  dispatch pads its operand through ``self.bucket_for`` (bucketed
  families compile once per prefill bucket);
- what ``warmup_engine`` actually warms: the engine methods it calls
  (``getattr(engine, "name")`` aliases included) and whether each call
  sits inside the ``for ... in engine.prefill_buckets`` loop.

Three checkers (``jit_surface_check.py``) consume the model; the
runtime recompile witness (``jitcheck.py``, ``DLLAMA_JITCHECK=1``)
proves at runtime what this model proves statically. Pure stdlib
``ast`` — no jax import, like the rest of the analyzer.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from .lockgraph import walk_excluding_nested_defs

# call spellings that create a compiled program
JIT_SPELLINGS = ("jax.jit",)
PARTIAL_SPELLINGS = ("partial", "functools.partial")
WARMUP_FN = "warmup_engine"
BUCKET_ITER_SUFFIX = ".prefill_buckets"


@dataclass
class JitSite:
    """One ``jax.jit`` occurrence."""

    name: str  # def name, or the bound attr for inline jax.jit(...) forms
    line: int
    donate: tuple[int, ...] = ()
    static_argnames: tuple[str, ...] = ()
    # "family": dispatched at serving time; "init": immediately invoked
    # at construction (compiles before warmup by construction); "free":
    # a module-level jit nothing binds to an engine attr
    kind: str = "family"
    factory: str | None = None  # enclosing jit-returning factory, if any


@dataclass
class Dispatcher:
    """An engine method that dispatches compiled families."""

    name: str
    line: int
    families: set[str] = field(default_factory=set)  # family attrs called
    bucketed: bool = False  # pads a host operand via self.bucket_for(...)
    # one DonateUse per donated argument of each donating call — the
    # donation-discipline checker's raw material
    donate_calls: list["DonateUse"] = field(default_factory=list)


@dataclass
class DonateUse:
    """One donated argument at one call site, with the facts the
    donation-discipline check needs: was the donated expression rebound
    by the call's own assignment targets, is it read again afterwards,
    did it escape into host-side state before the call."""

    family: str
    line: int  # the call
    spelling: str  # the donated argument, as spelled (`self.cache`)
    rebound: bool  # appears among the call statement's assignment targets
    later_read_line: int | None = None  # first Load after the call
    escape_line: int | None = None  # stored into other self-state pre-call


@dataclass
class WarmupCall:
    method: str
    line: int
    in_bucket_loop: bool = False


@dataclass
class JitModel:
    display: str
    sites: list[JitSite] = field(default_factory=list)
    # engine-attr -> the jit site it binds ("_decode_fn" -> _decode)
    families: dict[str, JitSite] = field(default_factory=dict)
    family_lines: dict[str, int] = field(default_factory=dict)
    dispatchers: dict[str, Dispatcher] = field(default_factory=dict)
    has_warmup: bool = False
    warmup_line: int = 0
    warmed: dict[str, WarmupCall] = field(default_factory=dict)

    def warmed_families(self) -> set[str]:
        """Family attrs reachable from a method ``warmup_engine`` calls."""
        out: set[str] = set()
        for m in self.warmed:
            d = self.dispatchers.get(m)
            if d is not None:
                out |= d.families
        return out


def _spelled(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on parsed trees
        return ""


def _int_tuple(node: ast.AST) -> tuple[int, ...]:
    """``(1,)`` / ``(0, 1)`` / ``1`` keyword values -> ints."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_tuple(node: ast.AST) -> tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


def _jit_decorator_site(fn) -> JitSite | None:
    """``@partial(jax.jit, donate_argnums=(1,))`` / ``@jax.jit``."""
    for dec in fn.decorator_list:
        if _spelled(dec) in JIT_SPELLINGS:
            return JitSite(fn.name, fn.lineno)
        if isinstance(dec, ast.Call) and _spelled(dec.func) in PARTIAL_SPELLINGS \
                and dec.args and _spelled(dec.args[0]) in JIT_SPELLINGS:
            donate: tuple[int, ...] = ()
            statics: tuple[str, ...] = ()
            for kw in dec.keywords:
                if kw.arg == "donate_argnums":
                    donate = _int_tuple(kw.value)
                elif kw.arg == "static_argnames":
                    statics = _str_tuple(kw.value)
            return JitSite(fn.name, fn.lineno, donate=donate,
                           static_argnames=statics)
    return None


def _jit_call_site(value: ast.AST, bound_name: str) -> JitSite | None:
    """``jax.jit(...)`` / ``jax.jit(...)()`` on an assignment's value."""
    if isinstance(value, ast.Call) and _spelled(value.func) in JIT_SPELLINGS:
        donate: tuple[int, ...] = ()
        for kw in value.keywords:
            if kw.arg == "donate_argnums":
                donate = _int_tuple(kw.value)
        return JitSite(bound_name, value.lineno, donate=donate)
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Call) \
            and _spelled(value.func.func) in JIT_SPELLINGS:
        # immediately invoked: jax.jit(init_fn, ...)() — compiles at
        # construction time, never dispatched again
        return JitSite(bound_name, value.lineno, kind="init")
    return None


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _Extractor(ast.NodeVisitor):
    def __init__(self, model: JitModel):
        self.model = model
        # def name -> site, for binding `self._x = _decode`
        self.sites_by_name: dict[str, JitSite] = {}
        # factory name -> the inner jit site it returns
        self.factories: dict[str, JitSite] = {}

    # -- pass 1: every jit site + factory ---------------------------------

    def collect_sites(self, tree: ast.Module) -> None:
        stack: list[ast.AST] = []

        def rec(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                site = _jit_decorator_site(node)
                if site is not None:
                    self.model.sites.append(site)
                    self.sites_by_name[site.name] = site
                    # a jit-returning factory: the nearest enclosing def
                    # that returns this jit by name
                    for outer in reversed(stack):
                        if isinstance(outer, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
                            if self._returns_name(outer, site.name):
                                site.factory = outer.name
                                self.factories[outer.name] = site
                            break
            elif isinstance(node, ast.Assign):
                targets = [t for t in node.targets]
                bound = None
                for t in targets:
                    a = _self_attr(t)
                    if a is not None:
                        bound = a
                        break
                    if isinstance(t, ast.Name):
                        bound = bound or t.id
                site = _jit_call_site(node.value, bound or "<anon>")
                if site is not None:
                    self.model.sites.append(site)
                    if bound is not None:
                        self.sites_by_name.setdefault(bound, site)
            stack.append(node)
            for child in ast.iter_child_nodes(node):
                rec(child)
            stack.pop()

        rec(tree)

    @staticmethod
    def _returns_name(fn, name: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and isinstance(node.value, ast.Name) \
                    and node.value.id == name:
                return True
        return False

    # -- pass 2: family attr bindings --------------------------------------

    def collect_families(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            site = self._site_of_value(node.value)
            if site is None or site.kind == "init":
                # init-kind jits compile at construction, before warmup
                # by construction — not dispatchable families
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None and isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)  # self._decode_multi_fns[h]
                if attr is not None and attr not in self.model.families:
                    self.model.families[attr] = site
                    self.model.family_lines[attr] = node.lineno

    def _site_of_value(self, value: ast.AST) -> JitSite | None:
        if isinstance(value, ast.Name):
            if value.id in self.factories:
                return self.factories[value.id]
            return self.sites_by_name.get(value.id)
        if isinstance(value, ast.Call):
            spelled = _spelled(value.func)
            if spelled in JIT_SPELLINGS:
                # direct `self.x = jax.jit(...)` — the site was recorded
                # under the bound attr in pass 1; look it up by line
                for s in self.model.sites:
                    if s.line == value.lineno:
                        return s
            if isinstance(value.func, ast.Name) \
                    and value.func.id in self.factories:
                return self.factories[value.func.id]
            attr = _self_attr(value.func)
            if attr is not None and attr in self.model.families:
                return self.model.families[attr]
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Call) \
                and _spelled(value.func.func) in JIT_SPELLINGS:
            for s in self.model.sites:
                if s.kind == "init" and s.line == value.lineno:
                    return s
        return None

    # -- pass 3: dispatchers ------------------------------------------------

    def collect_dispatchers(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__":
                    continue
                d = self._scan_method(fn)
                if d.families or d.bucketed:
                    # methods may repeat across fixture classes; first wins
                    self.model.dispatchers.setdefault(fn.name, d)

    def _family_of_expr(self, expr: ast.AST,
                        aliases: dict[str, str]) -> str | None:
        """Resolve an expression to the family attr it denotes: direct
        ``self.X``, either branch of a conditional (``self._decode_exec
        if ... else self._decode_fn``), ``self.X[...]`` / ``self.X.get``
        dict lookups, factory calls ``self.X(...)``, local aliases."""
        attr = _self_attr(expr)
        if attr is not None and attr in self.model.families:
            return attr
        if isinstance(expr, ast.Name):
            return aliases.get(expr.id)
        if isinstance(expr, ast.IfExp):
            return (self._family_of_expr(expr.body, aliases)
                    or self._family_of_expr(expr.orelse, aliases))
        if isinstance(expr, ast.Subscript):
            return self._family_of_expr(expr.value, aliases)
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr == "get":
                return self._family_of_expr(expr.func.value, aliases)
            return self._family_of_expr(expr.func, aliases)
        return None

    def _scan_method(self, fn) -> Dispatcher:
        d = Dispatcher(fn.name, fn.lineno)
        aliases: dict[str, str] = {}
        tuples: dict[str, list[ast.AST]] = {}  # operand-tuple literals
        # alias/tuple collection first (lexical order is good enough: the
        # engine's aliases are assigned before use)
        for node in walk_excluding_nested_defs(fn):
            if isinstance(node, ast.Assign):
                if isinstance(node.value, ast.Tuple):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tuples[t.id] = list(node.value.elts)
                fam = self._family_of_expr(node.value, aliases)
                if fam is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases[t.id] = fam
        for node in walk_excluding_nested_defs(fn):
            if not isinstance(node, ast.Call):
                continue
            if _self_attr(node.func) == "bucket_for" or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "bucket_for"
            ):
                d.bucketed = True
                continue
            fam = None
            attr = _self_attr(node.func)
            if attr is not None and attr in self.model.families:
                fam = attr
            elif isinstance(node.func, ast.Name):
                fam = aliases.get(node.func.id)
            if fam is None:
                continue
            d.families.add(fam)
            site = self.model.families[fam]
            if site.donate:
                d.donate_calls.extend(
                    self._donate_uses(fn, node, fam, site, tuples)
                )
        return d

    def _donate_uses(self, fn, call: ast.Call, fam: str, site: JitSite,
                     tuples: dict[str, list[ast.AST]]) -> list[DonateUse]:
        args = list(call.args)
        if len(args) == 1 and isinstance(args[0], ast.Starred) \
                and isinstance(args[0].value, ast.Name):
            # fn(*operands) with `operands = (a, b, ...)` assigned in the
            # same function: substitute the tuple literal's elements
            args = tuples.get(args[0].value.id, args)
        donated: list[str] = []
        for i in site.donate:
            if i < len(args) and not isinstance(args[i], ast.Starred):
                donated.append(_spelled(args[i]))
        targets: list[str] = []
        for node in walk_excluding_nested_defs(fn):
            if isinstance(node, ast.Assign) and any(
                c is call for c in ast.walk(node.value)
            ):
                for t in node.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        targets.extend(_spelled(e) for e in t.elts)
                    else:
                        targets.append(_spelled(t))
        # the donated buffer is invalid once the call is dispatched; a
        # read past the call's own statement (end_lineno: operand lists
        # span lines) reads freed memory unless the same spelling was
        # rebound from the call result
        after = getattr(call, "end_lineno", call.lineno) or call.lineno
        out = []
        for s in donated:
            use = DonateUse(fam, call.lineno, s, rebound=s in targets)
            if not use.rebound:
                for node in walk_excluding_nested_defs(fn):
                    if isinstance(node, (ast.Name, ast.Attribute)) \
                            and isinstance(getattr(node, "ctx", None), ast.Load) \
                            and _spelled(node) == s \
                            and getattr(node, "lineno", 0) > after:
                        line = node.lineno
                        if use.later_read_line is None \
                                or line < use.later_read_line:
                            use.later_read_line = line
            for node in walk_excluding_nested_defs(fn):
                if isinstance(node, ast.Assign) \
                        and getattr(node, "lineno", 0) < call.lineno \
                        and _spelled(node.value) == s:
                    for t in node.targets:
                        if _self_attr(t) is not None and _spelled(t) != s:
                            use.escape_line = node.lineno
            out.append(use)
        return out

    # -- pass 4: warmup coverage --------------------------------------------

    def collect_warmup(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == WARMUP_FN:
                self.model.has_warmup = True
                self.model.warmup_line = node.lineno
                self._scan_warmup(node)
                return

    def _scan_warmup(self, fn) -> None:
        if not fn.args.args:
            return
        engine = fn.args.args[0].arg
        aliases: dict[str, str] = {}
        for node in walk_excluding_nested_defs(fn):
            # alias = getattr(engine, "method", default)
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name) \
                    and node.value.func.id == "getattr" \
                    and len(node.value.args) >= 2 \
                    and isinstance(node.value.args[0], ast.Name) \
                    and node.value.args[0].id == engine \
                    and isinstance(node.value.args[1], ast.Constant) \
                    and isinstance(node.value.args[1].value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = node.value.args[1].value
        # bucket-loop membership needs ancestry
        bucket_lines: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.For) \
                    and _spelled(node.iter).endswith(BUCKET_ITER_SUFFIX):
                for sub in ast.walk(node):
                    line = getattr(sub, "lineno", None)
                    if line is not None:
                        bucket_lines.add(line)
        for node in walk_excluding_nested_defs(fn):
            if not isinstance(node, ast.Call):
                continue
            method = None
            if isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == engine:
                method = node.func.attr
            elif isinstance(node.func, ast.Name) and node.func.id in aliases:
                method = aliases[node.func.id]
            if method is None:
                continue
            call = WarmupCall(method, node.lineno,
                              in_bucket_loop=node.lineno in bucket_lines)
            prev = self.model.warmed.get(method)
            if prev is None or (call.in_bucket_loop and not prev.in_bucket_loop):
                self.model.warmed[method] = call


def extract_jit_model(tree: ast.Module, display: str) -> JitModel:
    """Build the surface model for one file. Empty model (no sites) when
    the file compiles nothing — the checkers gate on that."""
    model = JitModel(display)
    ex = _Extractor(model)
    ex.collect_sites(tree)
    ex.collect_families(tree)
    ex.collect_dispatchers(tree)
    ex.collect_warmup(tree)
    return model


def jit_model_of(path: Path | str) -> JitModel:
    """The model for a real file on disk (rot-guard tests, --jit-table)."""
    p = Path(path)
    return extract_jit_model(
        ast.parse(p.read_text(encoding="utf-8")), p.as_posix()
    )
