#!/bin/bash
# Supervisor for the round's hardware evidence: wait for the TPU tunnel,
# then bank proof in VALUE order — bench.py artifact first (primary +
# serving + 8B north star + bf16 parity + longctx + in-bench sweep),
# then the standalone kernel sweep, then the stage probe. If the tunnel
# dies mid-attempt, go back to waiting; stop once a TPU-platform bench
# artifact is banked (BENCH_LIVE.json) or the deadline passes.
#
# Writes results under scripts/hw_evidence_<ts>/; never touches git (the
# foreground session commits banked artifacts to avoid index races).
set -u
DIR="$(cd "$(dirname "$0")" && pwd)"
REPO="$(dirname "$DIR")"
cd "$REPO"
DEADLINE=$(( $(date +%s) + ${EVIDENCE_MAX_S:-36000} ))

is_tpu_artifact() {  # $1 = bench stdout file
  python - "$1" <<'EOF'
import json, sys
plat = None
try:
    for line in open(sys.argv[1]):
        line = line.strip()
        if line.startswith("{"):
            plat = json.loads(line).get("platform")
except Exception:
    pass
sys.exit(0 if plat == "tpu" else 1)
EOF
}

# serving_disagg CPU-smoke leg: the phase is backend-free (mock-engine
# replicas, same determinism class as pod_serving's fleet gate), so it
# proves out BEFORE the tunnel wait instead of idling with it. The full
# bench run repeats the phase; this leg exists so an unattended loop
# still surfaces a disagg regression even when the tunnel never comes
# up. Result keys — or the failure — are merged into the banked
# artifact's phase_errors, the same slot NO_BACKEND lands in.
SMOKE_OUT="$DIR/disagg_smoke_$(date +%Y%m%d_%H%M%S).out"
BENCH_CHILD=1 BENCH_PHASE=serving_disagg BENCH_FORCE_CPU=1 GRAFT_SMALL=1 \
  timeout 300 python bench.py > "$SMOKE_OUT" 2> "$SMOKE_OUT.err"
SMOKE_RC=$?
echo "serving_disagg cpu smoke rc=$SMOKE_RC ($SMOKE_OUT)"

merge_disagg_smoke() {  # $1 = banked artifact (BENCH_LIVE.json)
  python - "$SMOKE_OUT" "$SMOKE_RC" "$1" <<'EOF'
import json, sys
smoke_path, rc, live_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
result = None
try:
    for line in open(smoke_path):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                pass
except OSError:
    pass
try:
    with open(live_path) as f:
        live = json.load(f)
except Exception:
    live = {}
if rc == 0 and result is not None:
    live.update({k: v for k, v in result.items()
                 if k.startswith("serving_disagg")})
    live["serving_disagg_cpu_smoke"] = "ok"
else:
    live["serving_disagg_cpu_smoke"] = "failed"
    err = f"serving_disagg_cpu_smoke: rc={rc}"
    prior = live.get("phase_errors", "")
    live["phase_errors"] = (f"{prior}; {err}" if prior else err)[-600:]
with open(live_path, "w") as f:
    json.dump(live, f)
EOF
}

# serving_prefix swap A/B CPU-smoke leg: the tiered-residency ladder
# (park < swap < rebuild TTFT) is determinism-class evidence that needs
# no TPU, so it proves out before the tunnel wait too. Leg A runs the
# phase with the host tier on (bench default), leg B with
# BENCH_KV_HOST_BYTES=0 — the escape hatch, where the middle rung
# degenerates to rebuild and swap traffic must read zero. Both legs'
# headline numbers (swap-in latency included) are merged into the
# banked artifact under prefix_swap_ab_* keys — never over the TPU
# run's own serving_prefix_* keys.
AB_TS=$(date +%Y%m%d_%H%M%S)
AB_ON_OUT="$DIR/prefix_swap_on_$AB_TS.out"
BENCH_CHILD=1 BENCH_PHASE=serving_prefix BENCH_FORCE_CPU=1 GRAFT_SMALL=1 \
  timeout 300 python bench.py > "$AB_ON_OUT" 2> "$AB_ON_OUT.err"
AB_ON_RC=$?
AB_OFF_OUT="$DIR/prefix_swap_off_$AB_TS.out"
BENCH_CHILD=1 BENCH_PHASE=serving_prefix BENCH_FORCE_CPU=1 GRAFT_SMALL=1 \
  BENCH_KV_HOST_BYTES=0 \
  timeout 300 python bench.py > "$AB_OFF_OUT" 2> "$AB_OFF_OUT.err"
AB_OFF_RC=$?
echo "serving_prefix swap A/B cpu smoke rc=$AB_ON_RC/$AB_OFF_RC ($AB_ON_OUT)"

merge_prefix_swap_ab() {  # $1 = banked artifact (BENCH_LIVE.json)
  python - "$AB_ON_OUT" "$AB_ON_RC" "$AB_OFF_OUT" "$AB_OFF_RC" "$1" <<'EOF'
import json, sys
on_path, on_rc = sys.argv[1], int(sys.argv[2])
off_path, off_rc = sys.argv[3], int(sys.argv[4])
live_path = sys.argv[5]

def last_json(path):
    result = None
    try:
        for line in open(path):
            line = line.strip()
            if line.startswith("{"):
                try:
                    result = json.loads(line)
                except json.JSONDecodeError:
                    pass
    except OSError:
        pass
    return result

on, off = last_json(on_path), last_json(off_path)
try:
    with open(live_path) as f:
        live = json.load(f)
except Exception:
    live = {}
if on_rc == 0 and off_rc == 0 and on is not None and off is not None:
    for leg, result in (("on", on), ("off", off)):
        for key in ("park_ttft_ms", "swap_ttft_ms", "rebuild_ttft_ms",
                    "swap_ins", "swap_outs", "swap_in_ms",
                    "host_hit_rate"):
            v = result.get(f"serving_prefix_{key}")
            if v is not None:
                live[f"prefix_swap_ab_{leg}_{key}"] = v
    live["prefix_swap_ab"] = "ok"
else:
    live["prefix_swap_ab"] = "failed"
    err = f"prefix_swap_ab: rc={on_rc}/{off_rc}"
    prior = live.get("phase_errors", "")
    live["phase_errors"] = (f"{prior}; {err}" if prior else err)[-600:]
with open(live_path, "w") as f:
    json.dump(live, f)
EOF
}

attempt=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  attempt=$((attempt + 1))
  TPU_PROBE_TIMEOUT_S=150 TPU_PROBE_INTERVAL_S=180 bash scripts/tpu_watch.sh || exit 1
  TS=$(date +%Y%m%d_%H%M%S)
  OUT="$DIR/hw_evidence_$TS"
  mkdir -p "$OUT"
  echo "attempt $attempt: tunnel alive, benching" > "$OUT/status"

  BENCH_DEADLINE=${BENCH_DEADLINE:-2400} timeout 2600 python bench.py \
    > "$OUT/bench.out" 2> "$OUT/bench.err"
  echo "bench rc=$?" >> "$OUT/status"
  if is_tpu_artifact "$OUT/bench.out"; then
    tail -1 "$OUT/bench.out" > "$REPO/BENCH_LIVE.json"
    merge_disagg_smoke "$REPO/BENCH_LIVE.json"
    merge_prefix_swap_ab "$REPO/BENCH_LIVE.json"
    echo "TPU artifact banked" >> "$OUT/status"
    # bonus evidence while the tunnel is up; each has its own timeout
    # --update-table: a winning dequant_* combo is written back into
    # ops/dequant_table.json so DLLAMA_DEQUANT=auto serves the measured
    # winner from the next start (the foreground session commits it)
    timeout "${SWEEP_BUDGET_S:-1200}" python scripts/kernel_sweep.py 240 \
      --update-table > "$OUT/kernel_sweep.log" 2>&1
    echo "kernel_sweep rc=$?" >> "$OUT/status"
    timeout "${PROBE_BUDGET_S:-600}" python scripts/stage_probe.py \
      > "$OUT/stage_probe.log" 2>&1
    echo "stage_probe rc=$?" >> "$OUT/status"
    echo DONE >> "$OUT/status"
    exit 0
  fi
  echo "no TPU artifact (tunnel died or CPU fallback); re-waiting" >> "$OUT/status"
  sleep 30
done
echo "evidence loop: deadline passed"
exit 1
