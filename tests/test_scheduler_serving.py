"""Round-3 serving-path capabilities: interleaved chunked prefill, on-device
sampling, bf16/sharded KV cache, and collective byte accounting.

Reference points: the fork's loop stalls every lane on admission
(src/app.cpp:360-366) and samples host-side from the logits pipe
(src/app.cpp:374-394); the engine here admits one bucket per scheduler
iteration and samples inside the compiled decode step.
"""

import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer


@pytest.fixture(scope="module")
def stack(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    engine = InferenceEngine(config, params, n_lanes=4, prefill_buckets=(8,))
    return config, engine, tok


# ---------------------------------------------------------------------------
# interleaved chunked prefill (VERDICT Weak #2)
# ---------------------------------------------------------------------------


def test_prefill_interleaves_with_decode(stack):
    """While a long prompt admits, an active lane keeps decoding. With
    fused prefill (the default) each admission chunk rides a dispatch
    that ALSO advances every decoding lane (``decode_prefill_fused``), so
    decoding never pauses at all; any chunk that still takes the
    synchronous path must have a decode step between it and the next one
    (the reference freezes all decoding for the whole admission
    prefill)."""
    config, engine, tok = stack
    calls = []
    real = {}

    def rec(name, label):
        fn = getattr(engine, name)
        real[name] = fn

        def wrapper(*a, **k):
            calls.append(label)
            return fn(*a, **k)

        setattr(engine, name, wrapper)

    rec("prefill_chunk", "prefill")
    rec("decode", "decode")
    rec("decode_spec", "decode")
    rec("decode_pipelined", "decode")
    rec("decode_prefill_fused", "fused")  # one chunk AND one decode step
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        # lane A: short prompt, long generation — becomes the active decoder
        a = sched.submit(Request(prompt="hello", max_tokens=40, temperature=0.0))
        while a.state.name != "GENERATING":
            time.sleep(0.005)
            assert not a.future.done(), a.error
        calls.clear()
        # lane B: long prompt = many buckets of 8
        long_prompt = "hello world " * 30
        b = sched.submit(Request(prompt=long_prompt, max_tokens=2, temperature=0.0))
        a.future.result(timeout=120)
        b.future.result(timeout=120)
    finally:
        sched.stop()
        for name, fn in real.items():
            setattr(engine, name, fn)

    n_chunks = calls.count("prefill") + calls.count("fused")
    assert n_chunks >= 4, f"expected many buckets, got {calls}"
    # the admission rode the live chain: decoding never stalled behind it
    assert calls.count("fused") > 0, f"no fused admission dispatch: {calls}"
    # any chunk pair without a decode between them must involve a fused
    # dispatch (which advances the decode lanes itself)
    for x, y in zip(calls, calls[1:]):
        if x == "prefill":
            assert y != "prefill", (
                f"consecutive sync prefill buckets stalled decoding: {calls}"
            )


def test_interleaved_results_match_sequential(stack):
    """Interleaving must not change outputs: same greedy tokens as a lone
    request."""
    config, engine, tok = stack
    long_prompt = "hello world " * 20

    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        solo = sched.submit(Request(prompt=long_prompt, max_tokens=6, temperature=0.0))
        solo.future.result(timeout=120)
        solo_tokens = list(solo.generated_tokens)

        a = sched.submit(Request(prompt="hello", max_tokens=30, temperature=0.0))
        while a.state.name != "GENERATING":
            time.sleep(0.005)
        b = sched.submit(Request(prompt=long_prompt, max_tokens=6, temperature=0.0))
        b.future.result(timeout=120)
        a.future.result(timeout=120)
        assert b.generated_tokens == solo_tokens
    finally:
        sched.stop()


# ---------------------------------------------------------------------------
# on-device sampling (VERDICT Weak #3)
# ---------------------------------------------------------------------------


def test_on_device_sampling_reproducible_and_cheap(stack):
    config, engine, tok = stack

    def run():
        sched = ContinuousBatchingScheduler(engine, tok)  # on-device default
        sched.start()
        try:
            req = sched.submit(
                Request(prompt="hello world", max_tokens=8, temperature=0.9,
                        topp=0.9, seed=1234)
            )
            req.future.result(timeout=120)
            return list(req.generated_tokens)
        finally:
            sched.stop()

    engine.stats.reset()
    t1 = run()
    snap = engine.stats.reset()
    t2 = run()
    assert t1 == t2, "seeded on-device sampling must reproduce"
    assert len(t1) == 8
    # host traffic per decode step is tokens-only (greedy+sampled int32 per
    # lane), never the [n_lanes, vocab] f32 block
    vocab_row_bytes = config.vocab_size * 4
    assert snap.decode_steps > 0
    per_step = snap.host_bytes_in / max(1, snap.decode_steps + 1)
    assert per_step < vocab_row_bytes / 4, (
        f"sampled decode still transfers logits: {per_step} B/step"
    )


def test_on_device_vs_host_sampling_both_work(stack):
    """host_sampling=True keeps the bit-exact reference path working."""
    config, engine, tok = stack
    sched = ContinuousBatchingScheduler(engine, tok, host_sampling=True)
    sched.start()
    try:
        req = sched.submit(
            Request(prompt="hello", max_tokens=6, temperature=0.7, seed=99)
        )
        assert isinstance(req.future.result(timeout=120), str)
        assert len(req.generated_tokens) == 6
    finally:
        sched.stop()


def test_sample_token_distribution_sane(stack):
    """On-device sampler picks the dominant token at low temperature."""
    config, engine, tok = stack
    row = np.full(config.vocab_size, -10.0, np.float32)
    row[7] = 10.0
    got = engine.sample_token(jnp.asarray(row), temp=0.5, topp=0.9, seed=0, pos=0)
    assert got == 7


# ---------------------------------------------------------------------------
# KV cache dtype + placement (VERDICT Weak #4)
# ---------------------------------------------------------------------------


def test_cache_dtype_default_matches_platform(stack, tiny_model):
    config, engine, tok = stack
    expect = jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32
    assert engine.cache.k.dtype == expect


def test_engine_on_mesh_places_cache(tiny_model):
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh

    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    mesh = make_mesh(MeshPlan(tp=2, dp=2))
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    engine = InferenceEngine(
        config, shard_params(params, mesh), n_lanes=4, mesh=mesh,
        cache_dtype=jnp.bfloat16,
    )
    spec = engine.cache.k.sharding.spec
    # [L, B, S, n_kv, hd] -> (None, dp, sp, tp, None); trailing Nones may be
    # omitted by jax
    padded = tuple(spec) + (None,) * (5 - len(spec))
    assert padded[1] == "dp" and padded[3] == "tp", spec
    assert engine.cache.k.dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# collective byte accounting (VERDICT Missing #2)
# ---------------------------------------------------------------------------


def test_collective_stats_on_mesh(tiny_model):
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    mesh = make_mesh(MeshPlan(tp=2))
    engine = InferenceEngine(config, shard_params(params, mesh), n_lanes=2, mesh=mesh)
    stats = engine.collective_stats()
    # a tp=2 decode step must communicate (the ZQ all-gather analogue)
    assert stats["total_bytes"] > 0, stats
    assert stats["n_collectives"] > 0
    assert engine.stats.sync_bytes_per_decode == stats["total_bytes"]
    # cached on second call
    assert engine.collective_stats() is stats


def test_collective_stats_hlo_parser():
    from distributed_llama_multiusers_tpu.parallel.comm_stats import (
        collective_stats_from_hlo,
    )

    hlo = """
      %ar = f32[4,256]{1,0} all-reduce(%x), replica_groups={}
      %ag = (bf16[2,128], bf16[2,128]) all-gather(%a, %b), dimensions={0}
      %st = f32[8]{0} all-reduce-start(%y)
      %dn = f32[8]{0} all-reduce-done(%st)
      %not = f32[4] add(%p, %q)
    """
    out = collective_stats_from_hlo(hlo)
    assert out["bytes_by_kind"]["all-reduce"] == 4 * 256 * 4 + 8 * 4
    assert out["bytes_by_kind"]["all-gather"] == 2 * (2 * 128 * 2)
    assert out["n_collectives"] == 3


def test_high_topp_requests_stay_on_device(tiny_model):
    """The on-device sampler is full-vocab EXACT (zero-flush serving), so
    the old top-k-truncation fallback classes — top_p >= 0.99, temp >=
    1.5 — sample on device like everyone else: ZERO logits transfers in
    default serving. host_sampling=True remains the bit-exact host
    Sampler escape hatch and still reads full-vocab logits per token."""
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )
    from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    engine = InferenceEngine(config, params, n_lanes=2)
    fetches = {"n": 0}
    real = engine.all_logits

    def counting(logits):
        fetches["n"] += 1
        return real(logits)

    engine.all_logits = counting
    sched = ContinuousBatchingScheduler(engine, Tokenizer(tiny_model["tokenizer"]))
    sched.start()
    try:
        on_device = Request(prompt="hello", max_tokens=4, temperature=0.8, topp=0.9, seed=3)
        sched.submit(on_device)
        on_device.future.result(timeout=300)
        assert fetches["n"] == 0, "ordinary sampled request transferred logits"

        for wide_kw in ({"topp": 1.0}, {"topp": 0.0}, {"temperature": 1.8}):
            wide = Request(prompt="hello", max_tokens=4, seed=3,
                           **{"temperature": 0.8, **wide_kw})
            sched.submit(wide)
            wide.future.result(timeout=300)
            assert wide.error is None and len(wide.generated_tokens) >= 1
        assert fetches["n"] == 0, "wide-nucleus request transferred logits"
        assert engine.stats.snapshot()["host_exact_lanes"] == 0
    finally:
        sched.stop()

    # the escape hatch still reads full-vocab logits per sampled token
    engine2 = InferenceEngine(config, params, n_lanes=2)
    real2 = engine2.all_logits
    engine2.all_logits = lambda logits: (
        fetches.__setitem__("host", fetches.get("host", 0) + 1) or real2(logits)
    )
    sched2 = ContinuousBatchingScheduler(
        engine2, Tokenizer(tiny_model["tokenizer"]), host_sampling=True
    )
    sched2.start()
    try:
        exact = Request(prompt="hello", max_tokens=4, temperature=0.8, topp=1.0, seed=3)
        sched2.submit(exact)
        exact.future.result(timeout=300)
    finally:
        sched2.stop()
    assert exact.error is None and len(exact.generated_tokens) >= 1
    # every sampled token (first included) came from full-vocab host logits
    assert fetches.get("host", 0) >= len(exact.generated_tokens), fetches
    assert engine2.stats.snapshot()["host_exact_lanes"] == 1
