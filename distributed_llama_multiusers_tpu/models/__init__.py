from .config import LlamaConfig
from .llama import LlamaParams, llama_forward, llama_forward_train, init_kv_cache
from .loader import (
    load_params_from_m,
    load_params_from_m_quantized,
    params_from_random,
    quantize_params,
)
