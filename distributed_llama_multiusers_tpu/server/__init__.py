from .http import ApiServer
