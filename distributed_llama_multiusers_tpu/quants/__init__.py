from .codec import (
    FloatType,
    Q40_BLOCK_SIZE,
    Q80_BLOCK_SIZE,
    Q40_BLOCK_BYTES,
    Q80_BLOCK_BYTES,
    quantize_q40,
    dequantize_q40,
    quantize_q80,
    dequantize_q80,
    q40_to_planar,
    q80_to_planar,
    tensor_bytes,
    float_type_name,
)
from .packed import (
    PackedQ40,
    pack_q40_from_blocks,
    pack_q40_host,
    pack_q40_planar,
    q40_matmul_xla,
    unpack_q40,
)
