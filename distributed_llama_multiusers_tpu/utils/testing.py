"""Test/dev-environment helpers.

Multi-chip behavior is validated on a virtual CPU device mesh, the TPU
analogue of the reference's fake-synchronizer + local-process-cluster test
strategy (src/nn/nn-executor.cpp:6-8, examples/n-workers.sh): the same GSPMD
partitioner and collectives run, just over host devices.
"""

from __future__ import annotations

import os
import sys


def force_cpu_mesh(n_devices: int = 8) -> None:
    """Force JAX onto `n_devices` virtual CPU devices. Call BEFORE any jax
    backend is initialized.

    Two things are needed in this environment:
    1. xla_force_host_platform_device_count so one host looks like a mesh.
    2. Dropping any pre-registered TPU PJRT plugin (this box's sitecustomize
       registers one at interpreter start whose init dials a network tunnel —
       even under JAX_PLATFORMS=cpu, backend discovery would block on it).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    try:
        import jax
        from jax._src import xla_bridge

        if xla_bridge._default_backend is not None:  # pragma: no cover
            raise RuntimeError("force_cpu_mesh() must run before JAX backends initialize")
        # jax may have been imported (and read JAX_PLATFORMS) before us
        jax.config.update("jax_platforms", "cpu")
        for name in list(xla_bridge._backend_factories):
            if name != "cpu":
                del xla_bridge._backend_factories[name]
                # keep the NAME known: modules imported later (e.g. pallas ->
                # checkify) register platform-specific lowerings and assert
                # is_known_platform; only the factory must go, not the name
                plugins = getattr(xla_bridge, "_nonexperimental_plugins", None)
                if plugins is not None:
                    plugins.add(name)
        plugins = getattr(xla_bridge, "_nonexperimental_plugins", None)
        if plugins is not None:
            plugins.add("tpu")
    except ImportError:
        pass


def greedy_rollout(engine, prompt, n):
    """Plain greedy decode of n tokens on lane 0 (other lanes idle);
    returns (produced tokens, final position). Shared by the speculative-
    decoding tests and the multichip dryrun's on-mesh acceptance check."""
    import numpy as np

    _, g, pos = engine.prefill(0, prompt)
    toks = [int(g)]
    tokens = np.zeros(engine.n_lanes, np.int32)
    positions = np.zeros(engine.n_lanes, np.int32)
    for _ in range(n - 1):
        tokens[0], positions[0] = toks[-1], pos
        _, greedy, _ = engine.decode(tokens, positions)
        toks.append(int(greedy[0]))
        pos += 1
    return toks, pos
