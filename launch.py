#!/usr/bin/env python
"""Model downloader + launcher (reference: launch.py).

Downloads pre-converted .m/.t files from the distributed-llama release
catalog (resumable, chunked), writes a run script, and optionally starts
`dllama chat` / `dllama-api`.

Usage:
    python launch.py                       # list models
    python launch.py llama3_2_1b_instruct_q40
    python launch.py llama3_2_1b_instruct_q40 --run api
"""

from __future__ import annotations

import os
import sys
import urllib.request

# name -> (model urls (multi-part concatenated in order), tokenizer url)
# catalog mirrors the reference's (launch.py:16-47; huggingface-hosted)
_HF = "https://huggingface.co/b4rtaz"
CATALOG: dict[str, tuple[list[str], str]] = {
    "llama3_1_8b_instruct_q40": (
        [f"{_HF}/Llama-3_1-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_llama3.1_instruct_q40.m?download=true"],
        f"{_HF}/Llama-3_1-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama_3_1.t?download=true",
    ),
    "llama3_1_405b_instruct_q40": (
        [f"{_HF}/Llama-3_1-405B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_llama31_405b_q40_{i}.m?download=true" for i in range(56)],
        f"{_HF}/Llama-3_1-405B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama_3_1.t?download=true",
    ),
    "llama3_2_1b_instruct_q40": (
        [f"{_HF}/Llama-3_2-1B-Instruct-Q40-Distributed-Llama/resolve/main/dllama_model_llama3.2-1b-instruct_q40.m?download=true"],
        f"{_HF}/Llama-3_2-1B-Instruct-Q40-Distributed-Llama/resolve/main/dllama_tokenizer_llama3_2.t?download=true",
    ),
    "llama3_2_3b_instruct_q40": (
        [f"{_HF}/Llama-3_2-3B-Instruct-Q40-Distributed-Llama/resolve/main/dllama_model_llama3.2-3b-instruct_q40.m?download=true"],
        f"{_HF}/Llama-3_2-3B-Instruct-Q40-Distributed-Llama/resolve/main/dllama_tokenizer_llama3_2.t?download=true",
    ),
    "llama3_3_70b_instruct_q40": (
        [f"{_HF}/Llama-3_3-70B-Instruct-Q40-Distributed-Llama/resolve/main/dllama_model_llama-3.3-70b_q40{s}.m?download=true" for s in ("", *(f"_{i}" for i in range(1, 11)))],
        f"{_HF}/Llama-3_3-70B-Instruct-Q40-Distributed-Llama/resolve/main/dllama_tokenizer_llama_3_3.t?download=true",
    ),
    "deepseek_r1_distill_llama_8b_q40": (
        [f"{_HF}/DeepSeek-R1-Distill-Llama-8B-Distributed-Llama/resolve/main/dllama_model_deepseek-r1-distill-llama-8b_q40.m?download=true"],
        f"{_HF}/DeepSeek-R1-Distill-Llama-8B-Distributed-Llama/resolve/main/dllama_tokenizer_deepseek-r1-distill-llama-8b.t?download=true",
    ),
}

CHUNK = 1 << 20


def download(url: str, path: str) -> None:
    """Resumable chunked download."""
    done = os.path.getsize(path) if os.path.exists(path) else 0
    req = urllib.request.Request(url)
    if done:
        req.add_header("Range", f"bytes={done}-")
    try:
        with urllib.request.urlopen(req) as r:
            total = done + int(r.headers.get("Content-Length", 0))
            mode = "ab" if done and r.status == 206 else "wb"
            with open(path, mode) as f:
                while True:
                    chunk = r.read(CHUNK)
                    if not chunk:
                        break
                    f.write(chunk)
                    done += len(chunk)
                    pct = 100 * done / total if total else 0
                    print(f"\r📀 {os.path.basename(path)}: {done >> 20} MB ({pct:.0f}%)", end="", flush=True)
    except urllib.error.HTTPError as e:
        if e.code == 416:  # already complete
            return
        raise
    print()


def fetch_model(name: str) -> tuple[str, str]:
    model_urls, tok_url = CATALOG[name]
    d = os.path.join("models", name)
    os.makedirs(d, exist_ok=True)
    model_path = os.path.join(d, f"dllama_model_{name}.m")
    tok_path = os.path.join(d, f"dllama_tokenizer_{name}.t")
    if not os.path.exists(model_path):
        parts = []
        for i, url in enumerate(model_urls):
            part = model_path + (f".part{i}" if len(model_urls) > 1 else "")
            download(url, part)
            parts.append(part)
        if len(parts) > 1:
            with open(model_path, "wb") as out:
                for p in parts:
                    with open(p, "rb") as f:
                        while True:
                            b = f.read(CHUNK)
                            if not b:
                                break
                            out.write(b)
                    os.remove(p)
        elif parts[0] != model_path:
            os.rename(parts[0], model_path)
    if not os.path.exists(tok_path):
        download(tok_url, tok_path)
    return model_path, tok_path


def write_run_script(name: str, model: str, tokenizer: str) -> str:
    path = f"run_{name}.sh"
    with open(path, "w") as f:
        f.write(
            "#!/bin/sh\n"
            f"python -m distributed_llama_multiusers_tpu.app.dllama chat \\\n"
            f"  --model {model} \\\n"
            f"  --tokenizer {tokenizer} \\\n"
            f"  --temperature 0.7 --topp 0.9 --max-seq-len 4096\n"
        )
    os.chmod(path, 0o755)
    return path


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] not in CATALOG:
        print("Usage: python launch.py <model> [--run chat|api]")
        print("Available models:")
        for name in CATALOG:
            print(f"  {name}")
        raise SystemExit(0 if len(sys.argv) < 2 else 1)
    name = sys.argv[1]
    model, tokenizer = fetch_model(name)
    script = write_run_script(name, model, tokenizer)
    print(f"✅ {script} written")
    if "--run" in sys.argv:
        mode = sys.argv[sys.argv.index("--run") + 1] if sys.argv.index("--run") + 1 < len(sys.argv) else "chat"
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        if mode == "api":
            from distributed_llama_multiusers_tpu.app.dllama_api import main as api_main

            api_main(["--model", model, "--tokenizer", tokenizer])
        else:
            from distributed_llama_multiusers_tpu.app.dllama import main as cli_main

            cli_main(["chat", "--model", model, "--tokenizer", tokenizer])


if __name__ == "__main__":
    main()
