"""dlint (distributed_llama_multiusers_tpu/analysis): the analyzer itself
AND its verdict on the real tree.

Two layers, per the PR-2 contract:

- **self-tests** — every checker gets known-bad and known-good fixture
  snippets (including waiver syntax), so the analyzer is regression-tested
  as a program, not just trusted on its current verdict;
- **the tier-1 gate** — the full package must analyze clean (zero
  non-baselined findings). A new unlocked counter bump, un-waived
  host-sync in the decode path, wall-clock read, busy-poll, or undeclared
  sharding axis anywhere in the package fails this test.

Pure-stdlib imports: these tests run without jax.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from distributed_llama_multiusers_tpu.analysis import (
    DEFAULT_BASELINE,
    PACKAGE_ROOT,
    Analyzer,
    analyze_paths,
    default_checkers,
    load_baseline,
)
from distributed_llama_multiusers_tpu.analysis.cli import main as dlint_main


def run_on(tmp_path: Path, files: dict[str, str], baseline: set | None = None):
    """Write fixture files under tmp_path and analyze them (no baseline
    unless given). Returns the finding list."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    analyzer = Analyzer(default_checkers())
    return analyzer.run([tmp_path], baseline=baseline or set(), root=tmp_path)


def checks_of(findings):
    return sorted(f.check for f in findings)


# -- the tier-1 gate ---------------------------------------------------------


def test_package_analyzes_clean():
    """THE gate: zero non-baselined findings over the real package. If this
    fails, either fix the finding, waive it in place with a reason, or (last
    resort) baseline it — see docs/LINT.md."""
    findings = analyze_paths()
    assert findings == [], "dlint findings on the tree:\n" + "\n".join(
        f.render() for f in findings
    )


def test_cli_runs_clean_with_shipped_baseline(capsys):
    assert dlint_main([]) == 0
    assert "clean" in capsys.readouterr().out


def test_shipped_baseline_is_empty():
    """Adoption fixed or waived everything; keep it that way."""
    assert load_baseline(DEFAULT_BASELINE) == set()


def test_real_decl_sites_are_collected():
    """The EngineStats/QosQueue declarations actually reach the checker
    (guards against the declaration syntax silently rotting)."""
    from distributed_llama_multiusers_tpu.analysis.core import Project
    from distributed_llama_multiusers_tpu.analysis.lock_check import GuardedByChecker
    import ast

    project = Project()
    checker = GuardedByChecker()
    for rel in ("runtime/engine.py", "serving/qos.py"):
        p = PACKAGE_ROOT / rel
        from distributed_llama_multiusers_tpu.analysis.core import SourceFile

        sf = SourceFile(
            path=p, display=rel, text=p.read_text(), tree=ast.parse(p.read_text())
        )
        checker.collect(sf, project)
    assert "decode_steps" in project.guarded
    assert "prefix_hits" in project.guarded
    assert "_deficit" in project.guarded
    assert project.guarded["_depth"][0] == frozenset({"_lock", "_not_empty"})


# -- guarded-by --------------------------------------------------------------

GUARDED_CLS = """
    import threading

    class Stats:
        _dlint_guarded_by = {("lock",): ("hits", "misses")}

        def __init__(self):
            self.lock = threading.Lock()
            self.hits = 0
            self.misses = 0
"""


def test_guarded_by_flags_unlocked_access(tmp_path):
    findings = run_on(tmp_path, {"m.py": GUARDED_CLS + """
        def bump(s):
            s.hits += 1
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "'s.hits'" in findings[0].message


def test_guarded_by_engine_stats_shape(tmp_path):
    """Acceptance-criterion demo: a guarded EngineStats-style counter
    accessed outside stats.lock is a finding, even through a chain base
    (self.engine.stats) and even when SOME lock is held — it must be the
    declared lock on the SAME base."""
    src = GUARDED_CLS + """
        class Scheduler:
            def __init__(self, engine):
                self.engine = engine

            def good(self):
                with self.engine.stats.lock:
                    self.engine.stats.hits += 1

            def bad_unlocked(self):
                self.engine.stats.hits += 1

            def bad_wrong_base(self, other):
                with other.stats.lock:
                    self.engine.stats.hits += 1
    """
    findings = run_on(tmp_path, {"m.py": src})
    assert checks_of(findings) == ["guarded-by", "guarded-by"]
    lines = {f.line for f in findings}
    assert len(lines) == 2


def test_guarded_by_accepts_lock_locked_and_init(tmp_path):
    findings = run_on(tmp_path, {"m.py": GUARDED_CLS + """
        class User:
            def ok_with(self, s):
                with s.lock:
                    s.hits += 1

            def _bump_locked(self, s):
                s.misses += 1  # caller holds s.lock by contract
    """})
    assert findings == []


def test_guarded_by_alternate_locks_and_waiver(tmp_path):
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class Q:
            _dlint_guarded_by = {("_lock", "_cv"): ("_depth",)}

            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._depth = 0

            def push(self):
                with self._cv:
                    self._depth += 1

            def empty(self):
                # dlint: ok[guarded-by] advisory racy read by contract
                return self._depth == 0
    """})
    assert findings == []


def test_guarded_by_closure_in_with_block_is_not_protected(tmp_path):
    """A closure defined inside `with lock:` runs after the lock is
    released — the enclosing with must not count across the def/lambda
    boundary."""
    findings = run_on(tmp_path, {"m.py": GUARDED_CLS + """
        def make_cb(s):
            with s.lock:
                cb = lambda: s.hits + 1
                def cb2():
                    return s.misses
            return cb, cb2
    """})
    assert checks_of(findings) == ["guarded-by", "guarded-by"]


def test_guarded_by_malformed_declaration(tmp_path):
    findings = run_on(tmp_path, {"m.py": """
        class Bad:
            _dlint_guarded_by = {("lock",): 42}
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "malformed" in findings[0].message


# -- host-sync ---------------------------------------------------------------


def test_host_sync_flags_unwaived_asarray_in_decode_path(tmp_path):
    """Acceptance-criterion demo: a new un-waived host sync in the decode
    path is a finding."""
    src = """
        import numpy as np

        def decode(logits):
            return np.asarray(logits)
    """
    findings = run_on(tmp_path, {"runtime/engine.py": src})
    assert checks_of(findings) == ["host-sync"]
    # the same code OUTSIDE the decode-path scope is not flagged
    assert run_on(tmp_path / "other", {"models/llama.py": src}) == []


def test_host_sync_waiver_suppresses(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        def decode(logits):
            # dlint: ok[host-sync] the one packed readback per step
            return np.asarray(logits)
    """})
    assert findings == []


def test_host_sync_flags_item_and_cast(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        def f(x, toks_np):
            a = x.item()
            b = int(x)
            c = int(toks_np[0])  # *_np host-array convention: exempt
            return a, b, c
    """})
    assert checks_of(findings) == ["host-sync", "host-sync"]


def test_host_sync_cast_rule_is_engine_only(tmp_path):
    findings = run_on(tmp_path, {"runtime/scheduler.py": """
        def f(greedy):
            return int(greedy[0])  # host numpy from the engine: fine here
    """})
    assert findings == []


def test_host_sync_implicit_bool_on_compiled_step_output(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        class E:
            def step(self, x):
                logits, toks = self._decode_fn(x)
                if logits:
                    return toks
                return None
    """})
    assert checks_of(findings) == ["host-sync"]
    assert "implicit bool" in findings[0].message


def test_host_sync_covers_telemetry_package(tmp_path):
    """PR-5 satellite: the telemetry package is registered under host-sync
    — a device->host transfer construct added to a telemetry hot path
    (the scheduler calls these hooks from inside the serving loop) is a
    finding there exactly like in runtime/."""
    bad = """
        import numpy as np

        def on_token(tokens):
            return np.asarray(tokens)
    """
    findings = run_on(tmp_path, {"telemetry/spans.py": bad})
    assert checks_of(findings) == ["host-sync"]
    # metrics.py is scoped too; .item() is the other transfer spelling
    findings = run_on(tmp_path / "b", {"telemetry/metrics.py": """
        def observe(h, v):
            h.observe(v.item())
    """})
    assert checks_of(findings) == ["host-sync"]
    # the clean shape: host floats in, host floats out — no findings
    clean = run_on(tmp_path / "c", {"telemetry/hub.py": """
        import time

        def on_step(tracer, t0):
            tracer.slice("step.sync", "pipeline", t0, time.perf_counter())
    """})
    assert clean == []


def test_clock_covers_telemetry_files(tmp_path):
    """clock is package-wide, telemetry included: a wall-clock duration in
    a telemetry file is a finding; the one sanctioned absolute-timestamp
    site (the JSON log envelope) carries a waiver in the real tree."""
    findings = run_on(tmp_path, {"telemetry/logs.py": """
        import time

        def stamp():
            return time.time()
    """})
    assert checks_of(findings) == ["clock"]


def test_real_telemetry_guard_decls_are_collected():
    """The SpanTracer/metrics declarations reach the guarded-by checker
    (same rot-guard as the EngineStats/QosQueue assertion above)."""
    import ast

    from distributed_llama_multiusers_tpu.analysis.core import Project, SourceFile
    from distributed_llama_multiusers_tpu.analysis.lock_check import GuardedByChecker

    project = Project()
    checker = GuardedByChecker()
    for rel in ("telemetry/spans.py", "telemetry/metrics.py"):
        p = PACKAGE_ROOT / rel
        sf = SourceFile(
            path=p, display=rel, text=p.read_text(), tree=ast.parse(p.read_text())
        )
        checker.collect(sf, project)
    assert "_trace_ring" in project.guarded
    assert "_hist_counts" in project.guarded
    assert "_reg_metrics" in project.guarded
    assert project.guarded["_trace_dropped"][0] == frozenset({"_trace_lock"})


def test_guarded_by_flags_unlocked_telemetry_ring_access(tmp_path):
    """A new unlocked touch of the tracer ring state is a finding — the
    telemetry satellite's known-bad fixture."""
    findings = run_on(tmp_path, {"telemetry/spans.py": """
        import threading

        class SpanTracer:
            _dlint_guarded_by = {("_trace_lock",): ("_trace_ring",)}

            def __init__(self):
                self._trace_lock = threading.Lock()
                self._trace_ring = []

            def bad_append(self, ev):
                self._trace_ring.append(ev)

            def good_append(self, ev):
                with self._trace_lock:
                    self._trace_ring.append(ev)
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "_trace_ring" in findings[0].message


# -- paged KV pool (runtime/kvpool.py) ---------------------------------------


def test_host_sync_covers_kvpool_file(tmp_path):
    """PR-11 satellite: runtime/kvpool.py is registered under host-sync —
    the pool bookkeeping runs inside the admission path
    (scheduler._start_request -> engine.paged_admit) and is host
    dicts/lists by contract; a device->host transfer construct added
    there is a finding exactly like in runtime/."""
    findings = run_on(tmp_path, {"runtime/kvpool.py": """
        import numpy as np

        class KVPagePool:
            def admit(self, tokens):
                return np.asarray(tokens)
    """})
    assert checks_of(findings) == ["host-sync"]
    # the clean shape: pure host bookkeeping — block the prompt into
    # content tuples, walk the tree dict, no transfer spelling anywhere
    clean = run_on(tmp_path / "b", {"runtime/kvpool.py": """
        class KVPagePool:
            def blocks(self, tokens, bs):
                return [
                    tuple(tokens[i : i + bs])
                    for i in range(0, len(tokens), bs)
                ]
    """})
    assert clean == []


def test_real_kvpool_guard_decls_are_collected():
    """KVPagePool's free-list/refcount/prefix-tree declaration reaches
    the guarded-by checker (the rot-guard pattern: the declaration
    syntax must not silently rot out of collection)."""
    import ast

    from distributed_llama_multiusers_tpu.analysis.core import Project, SourceFile
    from distributed_llama_multiusers_tpu.analysis.lock_check import GuardedByChecker

    project = Project()
    checker = GuardedByChecker()
    p = PACKAGE_ROOT / "runtime/kvpool.py"
    sf = SourceFile(
        path=p, display="runtime/kvpool.py", text=p.read_text(),
        tree=ast.parse(p.read_text()),
    )
    checker.collect(sf, project)
    assert "_free" in project.guarded
    assert "_nodes" in project.guarded
    assert "_parked" in project.guarded
    assert "cow_copies" in project.guarded
    assert project.guarded["_free"][0] == frozenset({"_lock"})
    # the swap tier's own declaration (HostTier._lock over the LRU store
    # and its counters) must keep reaching the checker too
    assert "_swapped" in project.guarded
    assert "_pending_swapouts" in project.guarded
    assert project.guarded["_swapped"][0] == frozenset({"_lock"})


def test_guarded_by_flags_unlocked_kvpool_free_list(tmp_path):
    """Known-bad: a pool free-list pop outside the lock (stats() races
    the scheduler thread through exactly this state) is a finding;
    the locked and *_locked-helper shapes stay clean."""
    findings = run_on(tmp_path, {"runtime/kvpool.py": """
        import threading

        class KVPagePool:
            _dlint_guarded_by = {("_lock",): ("_free", "_ref")}

            def __init__(self):
                self._lock = threading.Lock()
                self._free = [0, 1, 2]
                self._ref = [0, 0, 0]

            def bad_alloc(self):
                return self._free.pop()

            def good_alloc(self):
                with self._lock:
                    page = self._free.pop()
                    self._ref[page] = 1
                    return page

            def _deref_locked(self, page):
                self._ref[page] -= 1
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "_free" in findings[0].message


# -- pipeline-sync -----------------------------------------------------------


def test_pipeline_sync_flags_sync_in_dispatch_half(tmp_path):
    """Acceptance-criterion demo: a host-sync construct inside the
    pipelined dispatch half is a finding (on top of the file-wide host-sync
    rule) — the dispatch half must enqueue device work from host metadata
    only, or the async chain silently re-serializes."""
    findings = run_on(tmp_path, {"runtime/scheduler.py": """
        import numpy as np

        class Sched:
            def _pipeline_dispatch(self, live, pl_pos, feed):
                arr = np.asarray(feed)
                self.engine.decode_pipelined(arr)
    """})
    assert "pipeline-sync" in checks_of(findings)
    # the same sync OUTSIDE the dispatch half is host-sync's business only
    other = run_on(tmp_path / "other", {"runtime/scheduler.py": """
        import numpy as np

        class Sched:
            def _pipeline_consume(self, live):
                # dlint: ok[host-sync] the lagged per-step readback
                return np.asarray(self.engine.pipeline_consume())
    """})
    assert "pipeline-sync" not in checks_of(other)


def test_pipeline_sync_clean_dispatch_half(tmp_path):
    """Building host metadata arrays and dispatching is exactly what the
    dispatch half is for — no findings."""
    findings = run_on(tmp_path, {"runtime/scheduler.py": """
        import numpy as np

        class Sched:
            def _pipeline_dispatch(self, live, pl_pos, feed):
                positions = np.full(4, 128, np.int32)
                for i, lane in live.items():
                    positions[i] = pl_pos[i]
                self.engine.decode_pipelined(positions, tokens=feed)
    """})
    assert findings == []


def test_pipeline_sync_implicit_bool_and_cast(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        class E:
            def decode_pipelined(self, positions, tokens=None):
                nxt, packed, self.cache = self._decode_pl_fn(positions)
                if nxt:
                    return int(packed)
                return None
    """})
    pipeline = [f for f in findings if f.check == "pipeline-sync"]
    msgs = " ".join(f.message for f in pipeline)
    assert "implicit bool" in msgs and "cast" in msgs


def test_pipeline_sync_covers_fused_dispatch(tmp_path):
    """The fused prefill+decode admission step is a dispatch half too: a
    host-sync construct inside ``engine.decode_prefill_fused`` (or the
    fused branch of ``_pipeline_dispatch``) re-serializes the chain at the
    exact moment it is supposed to hide admission work — a finding."""
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_prefill_fused(self, positions, chunk=None, tokens=None):
                nxt, packed, self.cache = self._decode_prefill_fn(positions)
                return np.asarray(packed)
    """})
    assert "pipeline-sync" in checks_of(findings)
    # the clean shape: host chunk data goes IN, nothing comes back
    clean = run_on(tmp_path / "clean", {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_prefill_fused(self, positions, chunk=None, tokens=None):
                padded = np.zeros(16, np.int32)
                padded[: len(chunk)] = chunk
                nxt, packed, self.cache = self._decode_prefill_fn(
                    positions, padded
                )
                self._pl_carry = nxt
                self._pl_inflight.append(packed)
    """})
    assert "pipeline-sync" not in checks_of(clean)


def test_pipeline_sync_covers_spec_pipelined_dispatch(tmp_path):
    """Zero-flush serving: the in-chain spec verify steps
    (``decode_spec_pipelined`` / ``decode_spec_prefill_fused``) are
    dispatch halves too — a host-sync construct inside them (reading the
    accept counts eagerly is the tempting bug) re-serializes the chain
    exactly when speculation was supposed to multiply with it."""
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_spec_pipelined(self, positions, drafts, draft_len,
                                      tokens=None):
                nxt, packed, self.cache = self._decode_spec_pl_fn(
                    positions, drafts
                )
                return np.asarray(packed)

            def decode_spec_prefill_fused(self, positions, drafts,
                                          draft_len, chunk=None,
                                          tokens=None):
                nxt, packed, self.cache = self._decode_spec_prefill_fn(
                    positions, drafts
                )
                return int(packed)
    """})
    checks = [f.check for f in findings if f.check == "pipeline-sync"]
    assert len(checks) == 2  # one per spec dispatch half
    # the clean shape: host draft candidates go IN, the packed verify
    # readback stays on device in the ring
    clean = run_on(tmp_path / "clean", {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_spec_pipelined(self, positions, drafts, draft_len,
                                      tokens=None):
                nxt, new_pos, packed, self.cache = self._decode_spec_pl_fn(
                    positions, drafts, draft_len
                )
                self._pl_carry = nxt
                self._pl_carry_pos = new_pos
                self._pl_inflight.append(("spec", packed))
    """})
    assert "pipeline-sync" not in checks_of(clean)


def test_pipeline_sync_draft_probe_branch_legal(tmp_path):
    """The draft-probing branch of ``_pipeline_dispatch`` is a pure
    host-side n-gram lookup — building candidate arrays from the lane's
    committed history is legal; syncing a device value to 'improve' the
    probe is a finding."""
    clean = run_on(tmp_path, {"runtime/scheduler.py": """
        import numpy as np

        class Sched:
            def _pipeline_dispatch(self, live, admitting, feed, spec_ok):
                positions = np.full(4, 128, np.int32)
                drafts = None
                draft_len = None
                for i, lane in live.items():
                    positions[i] = -1
                    d = lane.drafter.draft(lane.next_token, 4)
                    if len(d) >= 2:
                        if drafts is None:
                            drafts = np.zeros((4, 4), np.int32)
                            draft_len = np.zeros(4, np.int32)
                        drafts[i, : len(d)] = d
                        draft_len[i] = len(d)
                if drafts is None:
                    self.engine.decode_pipelined(positions, tokens=feed)
                else:
                    self.engine.decode_spec_pipelined(
                        positions, drafts, draft_len, tokens=feed
                    )
    """})
    assert "pipeline-sync" not in checks_of(clean)
    # probing off a DEVICE value instead of host history: a finding
    bad = run_on(tmp_path / "bad", {"runtime/scheduler.py": """
        import numpy as np

        class Sched:
            def _pipeline_dispatch(self, live, admitting, feed, spec_ok):
                carry = np.asarray(self.engine._pl_carry)
                self.engine.decode_spec_pipelined(carry)
    """})
    assert "pipeline-sync" in checks_of(bad)


def test_pipeline_sync_real_spec_dispatch_funcs_registered():
    """Rot-guard: the REAL engine/scheduler still define every dispatch
    half the check scopes, and the check's scope list names the spec
    families — a rename without a scope update would silently un-lint
    the zero-flush path."""
    import distributed_llama_multiusers_tpu.analysis.pipeline_check as pc
    from distributed_llama_multiusers_tpu.runtime.engine import (
        InferenceEngine,
    )
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
    )

    for name in ("decode_spec_pipelined", "decode_spec_prefill_fused"):
        assert name in pc.PIPELINE_FUNCS
        assert hasattr(InferenceEngine, name)
    assert "_pipeline_dispatch" in pc.PIPELINE_FUNCS
    assert hasattr(ContinuousBatchingScheduler, "_pipeline_dispatch")


def test_pipeline_sync_mesh_native_dispatch(tmp_path):
    """The mesh-native dispatch path (pod serving): sharding constraints
    on the device token carry are pure trace-time annotations — no
    finding — but reading the carry back to pick a shard (the tempting
    'just check the carry is replicated' bug) re-serializes the chain on
    every chip and IS one."""
    clean = run_on(tmp_path, {"runtime/engine.py": """
        import jax
        import numpy as np

        class E:
            def decode_pipelined(self, positions, tokens=None):
                feed = self._pl_carry if tokens is None else tokens
                feed = jax.lax.with_sharding_constraint(feed, self._tok_rep)
                nxt, packed, self.cache = self._decode_pl_fn(feed, positions)
                self._pl_carry = nxt
                self._pl_inflight.append(packed)
    """})
    assert "pipeline-sync" not in checks_of(clean)
    bad = run_on(tmp_path / "bad", {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_pipelined(self, positions, tokens=None):
                feed = self._pl_carry if tokens is None else tokens
                # 'verify' the carry landed replicated: a full device sync
                carry_host = np.asarray(feed)
                nxt, packed, self.cache = self._decode_pl_fn(
                    carry_host, positions
                )
                self._pl_carry = nxt
    """})
    assert "pipeline-sync" in checks_of(bad)


def test_pipeline_sync_waiver_suppresses(tmp_path):
    """A waiver naming BOTH overlapping checks silences the line (host-sync
    also scopes these files)."""
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_pipelined(self, positions, tokens=None):
                # dlint: ok[host-sync, pipeline-sync] probe build: deliberate sync
                return np.asarray(positions)
    """})
    assert findings == []


# -- clock -------------------------------------------------------------------


def test_clock_flags_time_time_everywhere(tmp_path):
    findings = run_on(tmp_path, {"anywhere/mod.py": """
        import time

        def seed():
            return int(time.time())
    """})
    assert checks_of(findings) == ["clock"]


def test_clock_accepts_monotonic_and_waived_timestamps(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        import time

        def dur():
            return time.monotonic() + time.perf_counter()

        def created():
            return int(time.time())  # dlint: ok[clock] absolute API timestamp
    """})
    assert findings == []


def test_clock_is_import_aware(tmp_path):
    """`from time import time` and `import time as t` must not bypass the
    wall-clock ban (the dotted-attribute spelling is not the only one)."""
    findings = run_on(tmp_path, {"a.py": """
        from time import time

        def deadline():
            return time() + 5.0
    """})
    assert checks_of(findings) == ["clock"]
    assert "from time import time" in findings[0].message
    findings = run_on(tmp_path / "b", {"b.py": """
        import time as t

        def seed():
            return int(t.time())
    """})
    assert checks_of(findings) == ["clock"]


def test_clock_flags_naive_datetime_now(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        from datetime import datetime

        def now():
            return datetime.now()
    """})
    assert checks_of(findings) == ["clock"]


# -- condvar -----------------------------------------------------------------


def test_condvar_wait_needs_predicate_loop(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._n = 0

            def bad(self):
                with self._cv:
                    self._cv.wait()

            def good_loop(self):
                with self._cv:
                    while self._n == 0:
                        self._cv.wait()

            def good_wait_for(self):
                with self._cv:
                    self._cv.wait_for(lambda: self._n > 0)
    """})
    assert checks_of(findings) == ["condvar"]
    assert "predicate loop" in findings[0].message


def test_condvar_flags_event_busy_poll(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        import threading

        class Loop:
            def __init__(self):
                self._stop = threading.Event()

            def bad(self):
                while not self._stop.is_set():
                    self._stop.wait(0.001)

            def good(self):
                self._stop.wait(0.25)
    """})
    assert checks_of(findings) == ["condvar"]
    assert "busy-poll" in findings[0].message


def test_condvar_daemon_thread_needs_join(tmp_path):
    bad = """
        import threading

        def serve():
            t = threading.Thread(target=print, daemon=True)
            t.start()
    """
    findings = run_on(tmp_path, {"mod.py": bad})
    assert checks_of(findings) == ["condvar"]
    assert "join" in findings[0].message
    good = """
        import threading

        class S:
            def start(self):
                self._t = threading.Thread(target=print, daemon=True)
                self._t.start()

            def stop(self):
                self._t.join(timeout=30)
    """
    assert run_on(tmp_path / "g", {"mod.py": good}) == []


# -- sharding-axis -----------------------------------------------------------


def test_sharding_axis_must_be_declared(tmp_path):
    """Acceptance-criterion demo: a PartitionSpec naming an axis the mesh
    builders never create is a finding."""
    findings = run_on(tmp_path, {
        "parallel/mesh.py": 'AXES = ("dp", "tp")\n',
        "parallel/sharding.py": """
            from jax.sharding import PartitionSpec as P

            GOOD = P("dp", None, "tp")
            BAD = P("dp", "model")
        """,
    })
    assert checks_of(findings) == ["sharding-axis"]
    assert "'model'" in findings[0].message


def test_sharding_axis_covers_collectives_and_shape_lookups(tmp_path):
    findings = run_on(tmp_path, {
        "parallel/mesh.py": 'AXES = ("dp", "tp", "sp")\n',
        "parallel/ops.py": """
            import jax

            def f(x, mesh):
                a = jax.lax.psum(x, "sp")
                b = jax.lax.ppermute(x, "ring", [(0, 1)])
                n = mesh.shape["tp"]
                m = mesh.shape.get("oops", 1)
                return a, b, n, m
        """,
    })
    assert checks_of(findings) == ["sharding-axis", "sharding-axis"]
    msgs = " ".join(f.message for f in findings)
    assert "'ring'" in msgs and "'oops'" in msgs


def test_sharding_axis_default_axes_without_decl(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        from jax.sharding import PartitionSpec as P

        OK = P("tp")
        BAD = P("nope")
    """})
    assert checks_of(findings) == ["sharding-axis"]


def test_sharding_axis_covers_ring_collectives(tmp_path):
    """The ring-collective entry points (ops/ring_collective.py) take the
    mesh axis name as a plain argument like the lax primitives they wrap —
    a misspelled axis there must be a lint finding, not a trace-time error
    on a real pod. Known-bad: bogus axes through every ring call shape;
    known-good: the declared axes pass clean."""
    findings = run_on(tmp_path, {
        "parallel/mesh.py": 'AXES = ("dp", "tp")\n',
        "ops/ring_collective.py": """
            import jax

            def sync(x, w, mesh, n):
                a = ring_reduce_scatter(x, "ring", n)
                b = ring_all_gather(a, "tp", n)
                c = ring_all_reduce(x, "tpx", n)
                d = ring_sync_matmul(x, w, mesh, axis="modell")
                return b, c, d
        """,
    })
    assert checks_of(findings) == ["sharding-axis"] * 3
    msgs = " ".join(f.message for f in findings)
    assert "'ring'" in msgs and "'tpx'" in msgs and "'modell'" in msgs
    clean = run_on(tmp_path / "clean", {
        "parallel/mesh.py": 'AXES = ("dp", "tp")\n',
        "ops/ring_collective.py": """
            import jax

            def sync(x, w, mesh, n):
                a = ring_reduce_scatter(x, "tp", n)
                b = ring_all_gather_q80(a, "tp", n)
                r = jax.lax.axis_index("tp")
                return ring_sync_matmul(x, w, mesh, axis="tp"), b, r
        """,
    })
    assert "sharding-axis" not in checks_of(clean)


def test_real_ring_collective_axis_sites_are_covered():
    """Rot-guard: the shipped ring_collective module really contains the
    call shapes the checker knows (ring calls with a positional or axis=
    axis name), so the vocabulary cannot silently drift from the code."""
    import ast

    from distributed_llama_multiusers_tpu.analysis.sharding_check import (
        COLLECTIVE_CALLS,
    )

    src = (
        PACKAGE_ROOT / "ops" / "ring_collective.py"
    ).read_text(encoding="utf-8")
    tree = ast.parse(src)
    called = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", None)
            if name in COLLECTIVE_CALLS:
                called.add(name)
    # the module itself exercises the ring vocabulary plus the lax
    # primitives underneath it
    assert {"ppermute", "axis_index"} <= called
    assert {"ring_reduce_scatter", "ring_all_gather"} & called


# -- lock-order (dlint v2 cross-file concurrency layer) ----------------------


TWO_LOCK_CLASSES = """
    import threading

    class A:
        def __init__(self):
            self._a_lock = threading.Lock()

    class B:
        def __init__(self):
            self._b_lock = threading.Lock()
"""


def test_lock_order_cycle_is_a_finding(tmp_path):
    """Acceptance-criterion demo: two call sites taking the same two locks
    in opposite orders is a lock-order cycle — the deadlock the test suite
    only reproduces under exactly the wrong interleaving becomes a lint
    failure instead."""
    findings = run_on(tmp_path, {"m.py": TWO_LOCK_CLASSES + """
        def forward(a, b):
            with a._a_lock:
                with b._b_lock:
                    pass

        def backward(a, b):
            with b._b_lock:
                with a._a_lock:
                    pass
    """})
    assert "lock-order" in checks_of(findings)
    msgs = " ".join(f.message for f in findings if f.check == "lock-order")
    assert "cycle" in msgs and "A._a_lock" in msgs and "B._b_lock" in msgs


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    findings = run_on(tmp_path, {"m.py": TWO_LOCK_CLASSES + """
        def one(a, b):
            with a._a_lock:
                with b._b_lock:
                    pass

        def two(a, b):
            with a._a_lock:
                with b._b_lock:
                    pass
    """})
    assert findings == []


def test_lock_order_cycle_across_files(tmp_path):
    """The graph is cross-file: each direction of the inversion lives in
    its own module and no single-file pass could see the cycle."""
    findings = run_on(tmp_path, {
        "serving/q.py": """
            import threading

            class Q:
                def __init__(self):
                    self._q_lock = threading.Lock()

                def visit(self, tracer):
                    with self._q_lock:
                        with tracer._t_lock:
                            pass
        """,
        "telemetry/t.py": """
            import threading

            class Tracer:
                def __init__(self):
                    self._t_lock = threading.Lock()

                def visit(self, q):
                    with self._t_lock:
                        with q._q_lock:
                            pass
        """,
    })
    assert "lock-order" in checks_of(findings)


def test_lock_order_one_level_call_edge(tmp_path):
    """A `with lock:` body calling a method that takes another known lock
    contributes an edge through the call — the cycle here is invisible to
    any with-statement-only analysis."""
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class Stats:
            def __init__(self):
                self._st_lock = threading.Lock()

            def bump(self):
                with self._st_lock:
                    pass

            def rev(self, q):
                with self._st_lock:
                    with q._q_lock:
                        pass

        class Queue:
            def __init__(self):
                self._q_lock = threading.Lock()

            def popped(self, stats):
                with self._q_lock:
                    stats.bump()
    """})
    lock_order = [f for f in findings if f.check == "lock-order"]
    assert lock_order, checks_of(findings)
    assert any("via" in f.message or "cycle" in f.message for f in lock_order)


def test_lock_order_self_reacquisition(tmp_path):
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class S:
            def __init__(self):
                self._s_lock = threading.Lock()

            def outer(self):
                with self._s_lock:
                    self.inner()

            def inner(self):
                with self._s_lock:
                    pass
    """})
    assert checks_of(findings) == ["lock-order"]
    assert "re-acquisition" in findings[0].message


def test_lock_order_condition_alias_is_not_an_edge(tmp_path):
    """Condition(self._lock) IS self._lock: nesting the condition inside
    the lock's own guarded-by sibling must not read as a second lock."""
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class Q:
            def __init__(self):
                self._lk = threading.Lock()
                self._cv = threading.Condition(self._lk)

            def pop(self):
                with self._cv:
                    while True:
                        self._cv.wait()
    """})
    assert findings == []


def test_lock_order_waiver_suppresses_edge(tmp_path):
    findings = run_on(tmp_path, {"m.py": TWO_LOCK_CLASSES + """
        def forward(a, b):
            with a._a_lock:
                with b._b_lock:
                    pass

        def backward(a, b):
            with b._b_lock:
                # dlint: ok[lock-order] shutdown path: forward() provably quiesced before this runs
                with a._a_lock:
                    pass
    """})
    assert findings == []


def test_lock_order_witness_name_mismatch(tmp_path):
    """make_lock literals are the runtime witness's vocabulary; a literal
    that drifts from its class-qualified declaration is a finding."""
    findings = run_on(tmp_path, {"m.py": """
        from distributed_llama_multiusers_tpu.lockcheck import make_lock

        class Q:
            def __init__(self):
                self._lk = make_lock("SomethingElse._lk")
    """})
    assert checks_of(findings) == ["lock-order"]
    assert "does not match" in findings[0].message


def test_real_lock_decls_are_collected():
    """Rot-guard: the real declarations the concurrency checks key on
    still exist, under their witness names, with the QosQueue condition
    aliased to its lock."""
    from distributed_llama_multiusers_tpu.analysis.lockgraph import scan_paths

    model = scan_paths([PACKAGE_ROOT])
    model.ensure_semantics()
    for qual in (
        "QosQueue._lock", "EngineStats.lock", "SpanTracer._trace_lock",
        "JsonLogger._log_lock", "Counter._m_lock", "Gauge._m_lock",
        "Histogram._m_lock", "MetricsRegistry._reg_lock", "native._lock",
        # failure containment (ISSUE 8): breaker/watchdog/fault-plan state
        # is lock-guarded and witness-wrapped like every other lock here
        "CircuitBreaker._lock", "StepWatchdog._lock", "FaultPlan._lock",
        # crash durability (ISSUE 10): journal queue, resume relays, and
        # recovery counters are lock-guarded and witness-wrapped too
        "RequestJournal._lock", "StreamRelay._lock",
        "StreamRegistry._lock", "RecoveryCoordinator._lock",
    ):
        assert qual in model.decls, f"lock declaration rotted: {qual}"
    assert model.canonical("QosQueue._not_empty") == "QosQueue._lock"
    # the watchdog condition is a view of its lock, same as the queue's
    assert model.canonical("StepWatchdog._cond") == "StepWatchdog._lock"
    # the journal/relay/registry conditions fold into their locks too
    assert model.canonical("RequestJournal._cv") == "RequestJournal._lock"
    assert model.canonical("StreamRelay._cv") == "StreamRelay._lock"
    assert model.canonical("StreamRegistry._cv") == "StreamRegistry._lock"


def test_host_sync_covers_containment_files(tmp_path):
    """ISSUE-8 satellite: the failure-containment files ride the serving
    loop (breaker fed per step, watchdog bracketing every blocking call,
    fault hooks inside dispatch paths) — a device->host transfer added to
    any of them is a host-sync finding like in runtime/."""
    bad = """
        import numpy as np

        def fire(point, value):
            return np.asarray(value)
    """
    for rel in ("serving/breaker.py", "serving/watchdog.py",
                "utils/faults.py"):
        findings = run_on(tmp_path / rel.replace("/", "_"), {rel: bad})
        assert checks_of(findings) == ["host-sync"], rel


def test_crash_durability_files_in_all_scopes(tmp_path):
    """ISSUE-10 satellite: serving/journal.py, serving/recovery.py and
    serving/resume.py ride the serving loop (admit/finish records
    enqueue from it, relay pushes run inside _consume, recovery
    re-admits through submit()) — so they sit in the host-sync scope,
    the package-wide clock ban, and the guarded-by discipline like the
    containment files before them. Known-bad fixtures per check, plus
    the clean shapes the real files use."""
    sync_bad = """
        import numpy as np

        def record(journal, value):
            journal.push(np.asarray(value))
    """
    clock_bad = """
        import time

        def stamp():
            return time.time()
    """
    for rel in ("serving/journal.py", "serving/recovery.py",
                "serving/resume.py"):
        tag = rel.replace("/", "_")
        findings = run_on(tmp_path / ("s_" + tag), {rel: sync_bad})
        assert checks_of(findings) == ["host-sync"], rel
        findings = run_on(tmp_path / ("c_" + tag), {rel: clock_bad})
        assert checks_of(findings) == ["clock"], rel
    # guarded-by: an unlocked touch of declared journal state is a
    # finding; the locked touch is clean (the real writer's shape)
    findings = run_on(tmp_path / "g", {"serving/journal.py": """
        import threading

        class RequestJournal:
            _dlint_guarded_by = {("_lock",): ("_j_pending",)}

            def __init__(self):
                self._lock = threading.Lock()
                self._j_pending = []

            def bad_enqueue(self, rec):
                self._j_pending.append(rec)

            def good_enqueue(self, rec):
                with self._lock:
                    self._j_pending.append(rec)
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "_j_pending" in findings[0].message
    # known-good: monotonic waits + locked state, the real files' idiom
    clean = run_on(tmp_path / "ok", {"serving/resume.py": """
        import threading
        import time

        class StreamRelay:
            _dlint_guarded_by = {("_lock", "_cv"): ("_rl_deltas",)}

            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._rl_deltas = []

            def push(self, index, text):
                with self._cv:
                    self._rl_deltas.append((index, text))
                    self._cv.notify_all()

            def wait_next(self, timeout):
                deadline = time.monotonic() + timeout
                with self._cv:
                    while not self._rl_deltas:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return None
                        self._cv.wait(remaining)
                    return self._rl_deltas[0]
    """})
    assert clean == []


# -- fleet front-end (fleet/) -------------------------------------------------


def test_host_sync_covers_fleet_files(tmp_path):
    """ISSUE-12 satellite: the fleet package is pure stdlib BY DESIGN
    (the router holds no model and no device) — a transfer spelling in
    any fleet module means device state leaked a layer up, and is a
    host-sync finding like in runtime/ and serving/."""
    bad = """
        import numpy as np

        def pick(keys):
            return np.asarray(keys)
    """
    for rel in ("fleet/balancer.py", "fleet/router.py",
                "fleet/migrate.py"):
        findings = run_on(tmp_path / rel.replace("/", "_"), {rel: bad})
        assert checks_of(findings) == ["host-sync"], rel
    # the clean shape: pure host hashing/bisect, the real balancer idiom
    clean = run_on(tmp_path / "ok", {"fleet/balancer.py": """
        import bisect
        import zlib

        def prefix_key(data, block):
            key = 0
            for b in range(len(data) // block):
                key = zlib.crc32(data[b * block:(b + 1) * block], key)
            return key

        def ring_find(ring, point):
            return bisect.bisect_left(ring, (point, ""))
    """})
    assert clean == []


def test_host_sync_covers_grammar_files(tmp_path):
    """ISSUE-13 satellite: the grammar package (schema compiler + slab)
    is pure-host numpy BY CONTRACT — it rides the admission and dispatch
    paths, so a device transfer spelling there would serialize every
    constrained dispatch on the automaton tables. Known-bad fixtures
    flag; the known-good shape (packbits/searchsorted host math, the
    real compiler idiom) stays clean; the shipped package keeps an
    empty baseline (test_package_analyzes_clean is the gate)."""
    bad = """
        import numpy as np

        def masks_of(rows):
            return np.asarray(rows)
    """
    for rel in ("grammar/automaton.py", "grammar/slab.py"):
        findings = run_on(tmp_path / rel.replace("/", "_"), {rel: bad})
        assert checks_of(findings) == ["host-sync"], rel
    bad_item = """
        def next_state(keys, key):
            return keys.searchsorted(key).item()
    """
    findings = run_on(tmp_path / "item", {"grammar/slab.py": bad_item})
    assert checks_of(findings) == ["host-sync"]
    # the clean shape: the compiler's real host idiom — packed masks and
    # sorted sparse edges, no transfer spellings anywhere
    clean = run_on(tmp_path / "ok", {"grammar/automaton.py": """
        import numpy as np

        def pack_masks(legal):
            bits = np.zeros((legal.shape[1], legal.shape[0]), np.uint8)
            bits[:, : legal.shape[0]] = legal.T
            return np.packbits(bits, axis=1, bitorder="little")

        def edge_lookup(keys, nexts, default, key):
            j = int(np.searchsorted(keys, key))
            if j < len(keys) and int(keys[j]) == key:
                return int(nexts[j])
            return int(default)
    """})
    assert clean == []


def test_real_fleet_balancer_guard_decls_are_collected():
    """FleetBalancer's replica-table declaration reaches the guarded-by
    checker (the rot-guard pattern: the declaration syntax must not
    silently rot out of collection)."""
    import ast

    from distributed_llama_multiusers_tpu.analysis.core import Project, SourceFile
    from distributed_llama_multiusers_tpu.analysis.lock_check import GuardedByChecker

    project = Project()
    checker = GuardedByChecker()
    p = PACKAGE_ROOT / "fleet/balancer.py"
    sf = SourceFile(
        path=p, display="fleet/balancer.py", text=p.read_text(),
        tree=ast.parse(p.read_text()),
    )
    checker.collect(sf, project)
    assert "_fb_replicas" in project.guarded
    assert "_fb_ring" in project.guarded
    assert "_fb_affinity_hits" in project.guarded
    assert project.guarded["_fb_replicas"][0] == frozenset({"_lock"})


def test_guarded_by_flags_unlocked_fleet_table(tmp_path):
    """Known-bad: a replica-table read outside the balancer lock (picks
    race the scrape thread through exactly this state) is a finding;
    the locked shape is clean."""
    findings = run_on(tmp_path, {"fleet/balancer.py": """
        import threading

        class FleetBalancer:
            _dlint_guarded_by = {("_lock",): ("_fb_replicas", "_fb_ring")}

            def __init__(self):
                self._lock = threading.Lock()
                self._fb_replicas = {}
                self._fb_ring = []

            def bad_pick(self, rid):
                return self._fb_replicas.get(rid)

            def good_pick(self, rid):
                with self._lock:
                    return self._fb_replicas.get(rid)
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "_fb_replicas" in findings[0].message


# -- lock-blocking ------------------------------------------------------------


def test_lock_blocking_flags_broadcast_under_lock(tmp_path):
    """'Never broadcast under a lock', mechanized: a control-packet send
    while holding any known lock serializes every pod process on one
    host's lock hold."""
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class Root:
            def __init__(self):
                self._r_lock = threading.Lock()

            def bad(self, plane, pkt):
                with self._r_lock:
                    plane.send_decode(pkt)
    """})
    assert checks_of(findings) == ["lock-blocking"]
    assert "send" in findings[0].message


def test_lock_blocking_flags_observer_call_under_lock(tmp_path):
    """The PR 5 wait-observer rule, mechanized: observer/hook callbacks
    run OUTSIDE the queue lock."""
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class Q:
            def __init__(self):
                self._wq_lock = threading.Lock()
                self._on_pop_wait = None

            def bad_pop(self, wait):
                with self._wq_lock:
                    self._on_pop_wait(wait)

            def good_pop(self, wait):
                with self._wq_lock:
                    observer = self._on_pop_wait
                return observer(wait)
    """})
    assert checks_of(findings) == ["lock-blocking"]
    assert "observer" in findings[0].message


def test_lock_blocking_flags_sleep_result_and_foreign_wait(tmp_path):
    findings = run_on(tmp_path, {"m.py": """
        import threading
        import time

        class W:
            def __init__(self):
                self._w_lock = threading.Lock()
                self._done = threading.Event()

            def bad_sleep(self):
                with self._w_lock:
                    time.sleep(0.5)

            def bad_future(self, fut):
                with self._w_lock:
                    return fut.result()

            def bad_foreign_wait(self):
                with self._w_lock:
                    self._done.wait(5.0)
    """})
    assert checks_of(findings) == ["lock-blocking"] * 3


def test_lock_blocking_own_condition_wait_is_fine(tmp_path):
    """cv.wait on the condition built over the held lock releases it —
    the one legitimate blocking-under-lock."""
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class Q:
            def __init__(self):
                self._bq_lock = threading.Lock()
                self._ready = threading.Condition(self._bq_lock)
                self._n = 0

            def pop(self):
                with self._ready:
                    while self._n == 0:
                        self._ready.wait()
    """})
    assert findings == []


def test_lock_blocking_one_level_call_expansion(tmp_path):
    """Calling a function that directly blocks, with the lock held, holds
    the lock across the block just the same — flagged at the call site."""
    findings = run_on(tmp_path, {"m.py": """
        import subprocess
        import threading

        _build_lock = threading.Lock()

        def compile_it():
            subprocess.run(["cc", "x.c"], check=True)

        def build():
            with _build_lock:
                compile_it()
    """})
    assert checks_of(findings) == ["lock-blocking"]
    assert "callee blocks" in findings[0].message


def test_lock_blocking_host_sync_set_under_lock(tmp_path):
    findings = run_on(tmp_path, {"m.py": """
        import threading
        import numpy as np

        class E:
            def __init__(self):
                self._e_lock = threading.Lock()

            def bad(self, logits):
                with self._e_lock:
                    return np.asarray(logits)
    """})
    assert checks_of(findings) == ["lock-blocking"]


# -- lock-atomicity -----------------------------------------------------------

GUARDED_DEPTH = """
    import threading

    class Q:
        _dlint_guarded_by = {("_at_lock",): ("_depth",)}

        def __init__(self):
            self._at_lock = threading.Lock()
            self._depth = 0
"""


def test_lock_atomicity_flags_split_rmw(tmp_path):
    """Acceptance-criterion demo: read under one hold, write under a
    later hold — each section is individually locked (guarded-by green)
    yet the interleaving loses updates."""
    findings = run_on(tmp_path, {"m.py": GUARDED_DEPTH + """
        def shrink(q):
            with q._at_lock:
                d = q._depth
            with q._at_lock:
                q._depth = d - 1
    """})
    assert checks_of(findings) == ["lock-atomicity"]
    assert "straddles" in findings[0].message


def test_lock_atomicity_check_then_act_variant(tmp_path):
    findings = run_on(tmp_path, {"m.py": GUARDED_DEPTH + """
        def maybe_shrink(q):
            with q._at_lock:
                has_items = q._depth > 0
            if has_items:
                with q._at_lock:
                    q._depth -= 1
    """})
    assert checks_of(findings) == ["lock-atomicity"]


def test_lock_atomicity_single_section_is_clean(tmp_path):
    """The shipped shape: read-modify-write folded into one hold; two
    disjoint WRITE-only sections are also fine (each += is atomic under
    its own hold)."""
    findings = run_on(tmp_path, {"m.py": GUARDED_DEPTH + """
        def shrink(q):
            with q._at_lock:
                q._depth = q._depth - 1

        def bump_twice(q, a, b):
            with q._at_lock:
                q._depth += a
            with q._at_lock:
                q._depth += b
    """})
    assert findings == []


def test_lock_atomicity_waiver(tmp_path):
    findings = run_on(tmp_path, {"m.py": GUARDED_DEPTH + """
        def optimistic(q):
            with q._at_lock:
                d = q._depth
            with q._at_lock:
                # dlint: ok[lock-atomicity] revalidated: d is a hint, the write re-checks under the lock
                q._depth = min(d, q._depth)
    """})
    assert findings == []


# -- pod-broadcast ------------------------------------------------------------


def test_pod_broadcast_flags_raise_between_send_and_pair(tmp_path):
    """Acceptance-criterion demo: a raise reachable after the packet went
    out but before the root's paired engine call — workers enter the
    collective the root never dispatches; the pod hangs."""
    findings = run_on(tmp_path, {"parallel/multihost.py": """
        class RootControlEngine:
            def decode(self, tokens):
                self._plane.send_decode(tokens)
                if not tokens:
                    raise ValueError("empty decode batch")
                return self._engine.decode(tokens)
    """})
    assert checks_of(findings) == ["pod-broadcast"]
    assert "raise" in findings[0].message and "deadlock" in findings[0].message


def test_pod_broadcast_flags_early_return(tmp_path):
    findings = run_on(tmp_path, {"parallel/multihost.py": """
        class RootControlEngine:
            def prefill(self, tokens):
                self._plane.send_prefill(tokens)
                if len(tokens) > 512:
                    return None
                return self._engine.prefill(tokens)
    """})
    assert checks_of(findings) == ["pod-broadcast"]
    assert "early return" in findings[0].message


def test_pod_broadcast_validate_first_is_clean(tmp_path):
    """The shipped shape: validation (raises) precedes the broadcast, the
    pair is the next engine call, and a return CONTAINING the pair is the
    pair, not an escape."""
    findings = run_on(tmp_path, {"parallel/multihost.py": """
        class RootControlEngine:
            def decode(self, tokens):
                if not tokens:
                    raise ValueError("empty decode batch")
                self._plane.send_decode(tokens)
                return self._engine.decode(tokens)

            def prefill(self, tokens, chunk):
                for off in range(0, len(tokens), chunk):
                    part = tokens[off : off + chunk]
                    self._plane.send_prefill(part)
                    out = self._engine.prefill(part)
                return out

            def stop_workers(self):
                self._plane.send_stop()
    """})
    assert findings == []


def test_pod_broadcast_scoped_to_multihost(tmp_path):
    """The same shape outside parallel/multihost.py is not this check's
    business."""
    findings = run_on(tmp_path, {"parallel/other.py": """
        class RootControlEngine:
            def decode(self, tokens):
                self._plane.send_decode(tokens)
                raise ValueError("nope")
    """})
    assert "pod-broadcast" not in checks_of(findings)


def test_pod_broadcast_real_sites_still_exist():
    """Rot-guard: the real RootControlEngine still broadcasts through
    self._plane.send_* with self._engine pairs — the exact spellings the
    check keys on. If this fails, the check went blind, not green."""
    import ast as ast_mod

    src = (PACKAGE_ROOT / "parallel" / "multihost.py").read_text()
    tree = ast_mod.parse(src)
    sends = pairs = 0
    for node in ast_mod.walk(tree):
        if isinstance(node, ast_mod.Call):
            spelled = ast_mod.unparse(node.func)
            if spelled.startswith("self._plane.send_"):
                sends += 1
            elif spelled.startswith("self._engine."):
                pairs += 1
    assert sends >= 8, f"only {sends} broadcast sites found"
    assert pairs >= 8, f"only {pairs} engine-pair sites found"
    assert "machine-checked" in src.splitlines()[0] or "pod-broadcast" in src


def test_pod_broadcast_return_after_pairless_send_is_legal(tmp_path):
    """OP_STOP-style ops replay no device program: an explicit trailing
    return after a pair-less broadcast is its normal shape (only a raise
    still flags — the packet is already out)."""
    findings = run_on(tmp_path, {"parallel/multihost.py": """
        class RootControlEngine:
            def stop_workers(self):
                self._plane.send_stop()
                return

            def bad_reset(self, ok):
                self._plane.send_stats_reset()
                if not ok:
                    raise RuntimeError("too late: the packet is out")
    """})
    assert checks_of(findings) == ["pod-broadcast"]
    assert "raise" in findings[0].message


def test_pod_broadcast_ignores_nested_def_returns(tmp_path):
    """A closure's return is its own call stack, not an escape of the
    proxy method."""
    findings = run_on(tmp_path, {"parallel/multihost.py": """
        class RootControlEngine:
            def decode(self, tokens):
                self._plane.send_decode(tokens)

                def fmt(x):
                    return x + 1
                return self._engine.decode(tokens, fmt)
    """})
    assert findings == []


def test_lock_blocking_local_lock_name_does_not_misbind(tmp_path):
    """A function-local `lock = threading.Lock()` is not shared state and
    must not resolve to an unrelated class's declared lock of the same
    attribute name (the EngineStats.lock mis-bind)."""
    findings = run_on(tmp_path, {"m.py": """
        import threading
        import time

        class Stats:
            def __init__(self):
                self.lock = threading.Lock()

        def scratch():
            lock = threading.Lock()
            with lock:
                time.sleep(0.1)
    """})
    assert findings == []


def test_lock_blocking_observer_attribute_spellings(tmp_path):
    """The documented observer vocabulary covers attribute callees too:
    renaming `_on_pop_wait` to `_wait_observer` must not retire the
    machine-checked wait-observer rule."""
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class Q:
            def __init__(self):
                self._ob_lock = threading.Lock()
                self._wait_observer = None
                self._done_callback = None

            def bad_a(self, w):
                with self._ob_lock:
                    self._wait_observer(w)

            def bad_b(self, w):
                with self._ob_lock:
                    self._done_callback(w)
    """})
    assert checks_of(findings) == ["lock-blocking", "lock-blocking"]


# -- CLI output formats & the lock-order graph dump ---------------------------


def test_cli_format_github_annotations(tmp_path, capsys):
    (tmp_path / "mod.py").write_text("import time\nT = time.time()\n")
    rc = dlint_main([str(tmp_path), "--no-baseline", "--format", "github"])
    assert rc == 1
    out = capsys.readouterr().out
    assert out.startswith("::error file=")
    assert "title=dlint[clock]" in out
    assert ",line=2," in out


def test_cli_format_sarif(tmp_path, capsys):
    import json

    (tmp_path / "mod.py").write_text("import time\nT = time.time()\n")
    rc = dlint_main([str(tmp_path), "--no-baseline", "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "dlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"lock-order", "lock-blocking", "lock-atomicity",
            "pod-broadcast", "clock"} <= rule_ids
    assert run["results"][0]["ruleId"] == "clock"
    line = run["results"][0]["locations"][0]["physicalLocation"]["region"]["startLine"]
    assert line == 2


def test_cli_format_sarif_clean_tree_emits_document(capsys):
    assert dlint_main(["--format", "sarif"]) == 0
    import json

    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"] == []


def test_cli_graph_dumps_dot(capsys):
    assert dlint_main(["--graph"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("digraph dlint_lock_order")
    assert '"QosQueue._lock"' in out
    assert "QosQueue._not_empty" in out  # the alias stays visible
    assert '"EngineStats.lock"' in out


def test_cli_graph_shows_edges_and_waived_style(tmp_path, capsys):
    (tmp_path / "m.py").write_text(textwrap.dedent("""
        import threading

        class A:
            def __init__(self):
                self._ga_lock = threading.Lock()

        class B:
            def __init__(self):
                self._gb_lock = threading.Lock()

        def nest(a, b):
            with a._ga_lock:
                # dlint: ok[lock-order] drawn dashed, not cycle-checked
                with b._gb_lock:
                    pass
    """))
    assert dlint_main([str(tmp_path), "--graph"]) == 0
    out = capsys.readouterr().out
    assert '"A._ga_lock" -> "B._gb_lock"' in out
    assert "style=dashed" in out


# -- waiver hygiene ----------------------------------------------------------


def test_bare_waiver_is_a_finding(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        import time

        def f():
            return time.time()  # dlint: ok[clock]
    """})
    # the bare waiver is rejected AND therefore does not suppress the clock
    # finding either
    assert checks_of(findings) == ["clock", "waiver"]
    assert "without a reason" in [f for f in findings if f.check == "waiver"][0].message


def test_unknown_check_name_in_waiver(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        X = 1  # dlint: ok[not-a-check] some reason
    """})
    assert checks_of(findings) == ["waiver"]
    assert "unknown check" in findings[0].message


def test_waiver_only_covers_named_check(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np
        import time

        def f(logits):
            # dlint: ok[clock] wrong check name for this line
            return np.asarray(logits)

        def g():
            return time.time()  # dlint: ok[host-sync] also wrong
    """})
    assert checks_of(findings) == ["clock", "host-sync"]


def test_star_waiver_and_standalone_placement(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        def f(logits):
            # dlint: ok[*] benchmark probe: sync everything on purpose
            return np.asarray(logits)
    """})
    assert findings == []


def test_waiver_in_string_literal_does_not_suppress(tmp_path):
    findings = run_on(tmp_path, {"mod.py": '''
        import time

        def f():
            doc = "# dlint: ok[clock] not a comment"
            return time.time(), doc
    '''})
    assert checks_of(findings) == ["clock"]


# -- baseline ----------------------------------------------------------------


def test_baseline_suppresses_only_listed_findings(tmp_path):
    files = {"mod.py": """
        import time

        def f():
            return time.time()

        def g():
            return datetime.datetime.now()

        import datetime
    """}
    all_findings = run_on(tmp_path, files)
    assert len(all_findings) == 2
    baseline = {all_findings[0].key}
    remaining = run_on(tmp_path, files, baseline=baseline)
    assert len(remaining) == 1
    assert remaining[0].key == all_findings[1].key


def test_write_baseline_roundtrip(tmp_path, capsys):
    (tmp_path / "mod.py").write_text("import time\nT = time.time()\n")
    bl = tmp_path / "bl.txt"
    assert dlint_main([str(tmp_path), "--baseline", str(bl), "--write-baseline"]) == 0
    assert bl.exists()
    capsys.readouterr()
    # with the written baseline the same tree is clean
    assert dlint_main([str(tmp_path), "--baseline", str(bl)]) == 0
    # without it, the finding is back
    assert dlint_main([str(tmp_path), "--no-baseline", "--baseline", str(bl)]) == 1


def test_write_baseline_excludes_unbaselinable_findings(tmp_path, capsys):
    """waiver/parse findings are never filtered by the baseline, so writing
    their keys would strand dead entries while the gate keeps failing; the
    CLI must report them and exit 1 instead."""
    (tmp_path / "mod.py").write_text(
        "import time\nT = time.time()  # dlint: ok[clock]\n"
    )
    bl = tmp_path / "bl.txt"
    rc = dlint_main([str(tmp_path), "--baseline", str(bl), "--write-baseline"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "cannot be baselined" in out
    keys = [
        line for line in bl.read_text().splitlines()
        if line.strip() and not line.startswith("#")
    ]
    # the clock finding (un-suppressed by the bare waiver) was baselined;
    # the waiver finding was not
    assert len(keys) == 1 and keys[0].startswith("clock\t")


def test_cli_missing_path_is_usage_error(tmp_path):
    assert dlint_main([str(tmp_path / "nope")]) == 2


def test_syntax_error_is_a_parse_finding(tmp_path):
    findings = run_on(tmp_path, {"mod.py": "def broken(:\n"})
    assert checks_of(findings) == ["parse"]


# -- fleet tracing (ISSUE 20) -------------------------------------------------


def test_host_sync_covers_tracectx(tmp_path):
    """ISSUE-20 satellite: the fleet trace context rides every router
    hop and the replica admission path (journal admit records), so it is
    registered under host-sync like the rest of telemetry/ — a transfer
    spelling there would mean device state leaked into the tracing
    layer. Known-bad fixtures flag; the real idiom (os.urandom ids,
    dict folding under a lock) stays clean."""
    findings = run_on(tmp_path, {"telemetry/tracectx.py": """
        import numpy as np

        def observe(phases):
            return np.asarray(list(phases.values()))
    """})
    assert checks_of(findings) == ["host-sync"]
    findings = run_on(tmp_path / "b", {"telemetry/tracectx.py": """
        def fold(totals, v):
            totals.append(v.item())
    """})
    assert checks_of(findings) == ["host-sync"]
    # the clean shape: the shipped module's real idiom
    clean = run_on(tmp_path / "c", {"telemetry/tracectx.py": """
        import os
        import threading

        def mint():
            return os.urandom(16).hex() + "-" + os.urandom(8).hex()

        class PhaseAccumulator:
            _dlint_guarded_by = {("_phase_lock",): ("_phase_counts",)}

            def __init__(self):
                self._phase_lock = threading.Lock()
                self._phase_counts = {}

            def observe(self, key):
                with self._phase_lock:
                    self._phase_counts[key] = (
                        self._phase_counts.get(key, 0) + 1
                    )
    """})
    assert clean == []


def test_real_tracing_guard_decls_are_collected():
    """Rot-guard for ISSUE 20's lock declarations: the shipped
    PhaseAccumulator, LabelledHistogram, and FleetRouter clock-offset
    declarations reach the guarded-by checker — the declaration syntax
    must not silently rot out of collection."""
    import ast

    from distributed_llama_multiusers_tpu.analysis.core import (
        Project,
        SourceFile,
    )
    from distributed_llama_multiusers_tpu.analysis.lock_check import (
        GuardedByChecker,
    )

    def collected(rel):
        project = Project()
        checker = GuardedByChecker()
        p = PACKAGE_ROOT / rel
        sf = SourceFile(path=p, display=rel, text=p.read_text(),
                        tree=ast.parse(p.read_text()))
        checker.collect(sf, project)
        return project.guarded

    guarded = collected("telemetry/tracectx.py")
    for attr in ("_phase_counts", "_phase_sums_ms", "_phase_records"):
        assert attr in guarded, attr
        assert guarded[attr][0] == frozenset({"_phase_lock"})
    guarded = collected("telemetry/metrics.py")
    assert "_hist_series" in guarded
    assert guarded["_hist_series"][0] == frozenset({"_m_lock"})
    guarded = collected("fleet/router.py")
    assert "_clock_offsets" in guarded
    assert guarded["_clock_offsets"][0] == frozenset({"_clock_lock"})


def test_guarded_by_flags_unlocked_phase_state(tmp_path):
    """Known-bad: phase-aggregation state read outside the accumulator
    lock (the router's stream pumps fold records from many client
    threads) is a finding; the locked shape is clean."""
    findings = run_on(tmp_path, {"telemetry/tracectx.py": """
        import threading

        class PhaseAccumulator:
            _dlint_guarded_by = {("_phase_lock",): ("_phase_counts",)}

            def __init__(self):
                self._phase_lock = threading.Lock()
                self._phase_counts = {}

            def bad_snapshot(self):
                return dict(self._phase_counts)

            def good_snapshot(self):
                with self._phase_lock:
                    return dict(self._phase_counts)
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "_phase_counts" in findings[0].message
