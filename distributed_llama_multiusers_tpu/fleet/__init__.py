"""Fleet front-end: multi-replica routing + journal-based live migration.

The layer ABOVE the per-replica serving stack (ROADMAP item 4). A
``dllama-router`` process spreads traffic across N ``dllama-api``
replicas using the signals each replica already emits — the ``/load``
JSON surface (queue depth, free lanes, paged-pool pressure, breaker
state, draining flag), typed 429/503 sheds with jittered Retry-After,
and the ``X-DLlama-Replica`` attribution header — and routes
same-leading-prompt sessions by consistent-hash prefix affinity so the
paged KV pool's warm prefix pages (runtime/kvpool.py) get multiplied
across the fleet instead of diluted by random placement.

Its signature capability is LIVE MIGRATION: PR 10's deterministic replay
(journal admit record -> byte-identical regeneration -> ``Last-Event-ID``
reattach) turned into a fleet primitive, so drains, rolling restarts and
replica death shed zero requests — see fleet/migrate.py and the
``/admin/session`` + ``/admin/migrate`` endpoints in server/http.py.

Pure stdlib like serving/ and telemetry/ (no jax, no numpy): the router
runs anywhere, and every module here is registered under dlint's
host-sync scope and lock discipline.
"""

from .balancer import (
    DEFAULT_AFFINITY_BLOCKS,
    DEFAULT_BLOCK_CHARS,
    FleetBalancer,
    ReplicaState,
    prefix_key,
    stable_hash,
)
from .migrate import (
    MigrationShed,
    fetch_ticket,
    inject_session,
    open_stream,
)
from .router import FleetRouter
