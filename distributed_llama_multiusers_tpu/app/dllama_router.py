"""`dllama-router` entry point: the fleet front-end (fleet/router.py).

One model-free process above N `dllama-api` replicas: prefix-affine
consistent-hash routing (same-system-prompt sessions land on the replica
holding the warm paged-KV prefix), least-loaded placement from each
replica's /load scrape, typed shed handling with honored Retry-After,
and journal-based live migration so drains, rolling restarts and replica
death shed zero requests (docs/SERVING.md "Fleet serving").

Deliberately import-light: no jax, no model loading — the router starts
in milliseconds and can front replicas on any backend.
"""

from __future__ import annotations

import signal
import threading

from ..disagg.prefill import DEFAULT_LONG_PROMPT_CHARS
from ..fleet import FleetRouter
from ..fleet.balancer import DEFAULT_AFFINITY_BLOCKS, DEFAULT_BLOCK_CHARS
from .args import build_router_parser


def log(emoji: str, msg: str) -> None:
    # runtime_setup.log without the jax import chain
    print(f"{emoji} {msg}", flush=True)


def main(argv=None) -> None:
    args = build_router_parser().parse_args(argv)
    block_chars = (
        DEFAULT_BLOCK_CHARS if args.affinity_block_chars is None
        else args.affinity_block_chars
    )
    blocks = (
        DEFAULT_AFFINITY_BLOCKS if args.affinity_blocks is None
        else args.affinity_blocks
    )
    threshold = (
        DEFAULT_LONG_PROMPT_CHARS if args.disagg_threshold is None
        else args.disagg_threshold
    )
    router = FleetRouter(
        list(args.replicas),
        affinity_block_chars=max(1, block_chars),
        affinity_blocks=max(0, blocks),
        scrape_interval_s=args.scrape_interval,
        migration=args.migration == "on",
        disagg=threshold > 0,
        long_prompt_chars=threshold,
    ).start()
    router.scrape_once()  # first routing decision sees real load state
    httpd = router.serve(host=args.host, port=args.port)
    log("⭐", f"Router listening on {args.host}:{args.port} over "
              f"{len(args.replicas)} replica(s): {', '.join(args.replicas)}")
    log("🧭", "prefix affinity "
              + (f"on ({blocks} x {block_chars} chars)" if blocks > 0
                 else "off")
              + f"; migration {args.migration}"
              + f"; disagg "
              + (f"on (long >= {threshold} chars -> prefill replicas)"
                 if threshold > 0 else "off"))

    def _sigterm(*_):
        log("⭐", "SIGTERM: router stopping (in-flight streams finish)")
        # dlint: ok[condvar] shutdown() must come from another thread (serve_forever runs on THIS one); nothing joins the helper
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        log("⭐", "Shutting down")
    finally:
        httpd.shutdown()
        router.close()


if __name__ == "__main__":
    main()
