"""On-device packed Q40 weights: pack/unpack exactness, quantized matmul,
full-model forward with quantized params, and quantized .m loading.

The reference analogue is matmul_Q80_Q40_F32 vs matmul_F32 equivalence in
src/nn/nn-cpu-ops-test.cpp:220-241 (tolerance there 4.0 on 4096-dim dots);
here dequantization is exact by construction, so the checks are tighter.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_llama_multiusers_tpu.quants.codec import (
    dequantize_q40,
    quantize_q40,
)
from distributed_llama_multiusers_tpu.quants.packed import (
    PackedQ40,
    pack_q40_from_blocks,
    pack_q40_host,
    q40_matmul_xla,
    unpack_q40,
)


def test_pack_unpack_matches_reference_dequant():
    rng = np.random.default_rng(0)
    d_out, d_in = 48, 64
    w = rng.standard_normal((d_out, d_in)).astype(np.float32)
    blocks = quantize_q40(w.reshape(-1))
    golden = dequantize_q40(blocks).reshape(d_out, d_in)  # reference dequant

    pk, sc = pack_q40_from_blocks(blocks, (d_out, d_in))
    assert pk.shape == (d_in // 2, d_out) and pk.dtype == np.uint8
    assert sc.shape == (d_in // 32, d_out) and sc.dtype == np.float16

    dev = unpack_q40(PackedQ40(jnp.asarray(pk), jnp.asarray(sc)), jnp.float32)
    np.testing.assert_array_equal(np.asarray(dev), golden.T)


def test_pack_q40_host_equals_pack_from_blocks():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((2, 16, 64)).astype(np.float32)  # [L, d_out, d_in]
    pk, sc = pack_q40_host(w)
    assert pk.shape == (2, 32, 16) and sc.shape == (2, 2, 16)
    for layer in range(2):
        blocks = quantize_q40(w[layer].reshape(-1))
        pk1, sc1 = pack_q40_from_blocks(blocks, (16, 64))
        np.testing.assert_array_equal(pk[layer], pk1)
        np.testing.assert_array_equal(sc[layer], sc1)


def test_q40_matmul_xla_matches_dense():
    rng = np.random.default_rng(2)
    d_in, d_out, b = 128, 96, 4
    w = rng.standard_normal((d_out, d_in)).astype(np.float32)
    x = rng.standard_normal((b, d_in)).astype(np.float32)
    pk, sc = pack_q40_host(w)
    pq = PackedQ40(jnp.asarray(pk), jnp.asarray(sc))

    golden_w = dequantize_q40(quantize_q40(w.reshape(-1))).reshape(d_out, d_in)
    want = x @ golden_w.T
    got = np.asarray(q40_matmul_xla(jnp.asarray(x), pq))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_forward_quantized_close_to_dense():
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        params_from_random,
        quantize_params,
    )
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig

    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=96, seq_len=32,
    )
    params = params_from_random(config, seed=3, dtype=jnp.float32)
    qparams = quantize_params(params)
    assert isinstance(qparams.layers.wq, PackedQ40)
    assert isinstance(qparams.wcls, PackedQ40)

    tokens = jnp.asarray(np.random.default_rng(4).integers(0, 96, (2, 8)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32)[None], (2, 8))
    logits_d, _ = llama_forward(config, params, tokens, positions, init_kv_cache(config, 2))
    logits_q, _ = llama_forward(config, qparams, tokens, positions, init_kv_cache(config, 2))
    # 4-bit weights: expect small but nonzero drift vs dense
    diff = np.abs(np.asarray(logits_q) - np.asarray(logits_d))
    assert np.isfinite(np.asarray(logits_q)).all()
    assert diff.mean() < 0.5, diff.mean()


def test_forward_quantized_exact_vs_host_dequantized_weights():
    """Dequantizing on device inside the matmul must equal running the dense
    forward on host-dequantized weights — dequant itself is lossless."""
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        params_from_random,
        quantize_params,
    )
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig

    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=96, seq_len=32,
    )
    params = params_from_random(config, seed=5, dtype=jnp.float32)
    qparams = quantize_params(params)

    def dq(w):
        if isinstance(w, PackedQ40):
            return unpack_q40(w, jnp.float32)
        return w

    dq_layers = qparams.layers._replace(
        **{k: dq(getattr(qparams.layers, k)) for k in ("wq", "wk", "wv", "wo", "w1", "w2", "w3")}
    )
    dq_params = qparams._replace(layers=dq_layers, wcls=dq(qparams.wcls))

    tokens = jnp.asarray(np.random.default_rng(6).integers(0, 96, (1, 4)), jnp.int32)
    positions = jnp.arange(4, dtype=jnp.int32)[None]
    logits_q, _ = llama_forward(config, qparams, tokens, positions, init_kv_cache(config, 1))
    logits_dq, _ = llama_forward(config, dq_params, tokens, positions, init_kv_cache(config, 1))
    np.testing.assert_allclose(np.asarray(logits_q), np.asarray(logits_dq), rtol=1e-6, atol=1e-6)


def test_load_params_from_m_quantized(tiny_model):
    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        load_params_from_m,
        load_params_from_m_quantized,
    )

    header = tiny_model["header"]
    path = tiny_model["model"]
    header2 = load_model_header(path)
    config, qparams = load_params_from_m_quantized(path, header2, dtype=jnp.float32)
    _, dparams = load_params_from_m(path, header2, dtype=jnp.float32)
    assert isinstance(qparams.layers.wq, PackedQ40)

    tokens = jnp.asarray([[1, 2, 3]], jnp.int32)
    positions = jnp.arange(3, dtype=jnp.int32)[None]
    logits_q, _ = llama_forward(config, qparams, tokens, positions, init_kv_cache(config, 1))
    logits_d, _ = llama_forward(config, dparams, tokens, positions, init_kv_cache(config, 1))
    # both paths dequantize the same Q40 bytes -> identical f32 weights
    np.testing.assert_allclose(
        np.asarray(logits_q), np.asarray(logits_d), rtol=1e-5, atol=1e-5
    )


def test_quantized_params_shard_and_forward_on_mesh():
    """PackedQ40 params must flow through shard_params + a TP forward (the
    reference runs Q40 weights sharded across nodes; here: across the mesh)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        params_from_random,
        quantize_params,
    )
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
        vocab_size=96, seq_len=32,
    )
    params = params_from_random(config, seed=7, dtype=jnp.float32)
    qparams = quantize_params(params)
    mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2))
    sharded = shard_params(qparams, mesh)
    assert isinstance(sharded.layers.wq, PackedQ40)

    tokens = jnp.asarray(np.random.default_rng(8).integers(0, 96, (2, 4)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32)[None], (2, 4))
    cache = init_kv_cache(config, 2)

    logits_sharded, _ = jax.jit(
        lambda p, t, pos, c: llama_forward(config, p, t, pos, c)
    )(sharded, tokens, positions, cache)
    logits_local, _ = llama_forward(config, qparams, tokens, positions, cache)
    np.testing.assert_allclose(
        np.asarray(logits_sharded), np.asarray(logits_local), rtol=2e-5, atol=2e-5
    )


def _q80_sync_fixture():
    import jax
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        params_from_random,
    )
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    config = LlamaConfig(
        dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
        vocab_size=96, seq_len=32,
    )
    mesh = make_mesh(MeshPlan(tp=2))
    params = shard_params(params_from_random(config, seed=5, dtype=jnp.float32), mesh)
    tokens = jnp.asarray(np.random.default_rng(4).integers(0, 96, (2, 4)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32)[None], (2, 4))

    def fwd(q80_sync):
        return jax.jit(
            lambda p, t, pos, c: llama_forward(
                config, p, t, pos, c, mesh=mesh, q80_sync=q80_sync
            )
        )

    cache = init_kv_cache(config, 2)
    return fwd, params, tokens, positions, cache


def test_q80_sync_matmul_parity_and_payload_drop():
    """--buffer-float-type q80 on a tp mesh ships the wo/w2 sync as int8+
    scales — outputs stay within Q80 tolerance of the f32-sync forward and
    the compiled program's collective payload drops (the reference's
    ZQ-pipe bandwidth claim, ~4x on the gather half; src/llm.cpp:150,
    SURVEY.md §5.8). This test pins the LEGACY psum_scatter+all_gather
    transport (parallel/collectives.q80_sync_matmul), which since PR 7 is
    the --ring-sync off escape-hatch lowering — the default routes the
    same wire format through the ring (companion test below)."""
    from distributed_llama_multiusers_tpu.ops.ring_collective import (
        ring_sync_enabled,
        set_ring_sync,
    )
    from distributed_llama_multiusers_tpu.parallel.comm_stats import collective_stats_of

    prev = ring_sync_enabled()
    try:
        set_ring_sync(False)
        fwd, params, tokens, positions, cache = _q80_sync_fixture()
        ref, _ = fwd(False)(params, tokens, positions, cache)
        got, _ = fwd(True)(params, tokens, positions, cache)
        # Q80 rounding noise only (int8 blocks, f16 scales)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0.15, rtol=0.05)
        assert not np.allclose(np.asarray(got), np.asarray(ref)), (
            "q80 path produced bit-identical logits — quantized sync not active?"
        )

        base = collective_stats_of(fwd(False), params, tokens, positions, cache)
        q80 = collective_stats_of(fwd(True), params, tokens, positions, cache)
        # the parser counts OUTPUT payload per op, which flatters all-reduce
        # (a ring all-reduce moves ~2x its payload on the wire, the rs+ag pair
        # exactly 1x each): f32 all-reduce 1.0 vs rs 0.5 + int8 ag ~0.27 = 0.77
        # measured here; on the wire the drop is ~(2.0 -> 0.77), ~2.6x
        assert q80["total_bytes"] < 0.8 * base["total_bytes"], (base, q80)
        # the int8 gather must be visible in the mix
        assert any(k.startswith("all-gather") for k in q80["bytes_by_kind"]), q80
    finally:
        set_ring_sync(prev)


def test_q80_sync_over_ring_parity_and_hlo_shape():
    """The PR-7 default: on a pure-TP mesh the q80 wire rides the RING
    (ops/ring_collective.ring_sync_matmul q80_wire) — same Q80 tolerance
    class vs the f32-sync forward, and the compiled program's collectives
    are chunk-sized collective-permutes (the overlappable hops), not one
    monolithic all-reduce, with int8 permutes visibly shrinking the
    payload vs the f32-wire ring."""
    from distributed_llama_multiusers_tpu.ops.ring_collective import (
        ring_sync_enabled,
        set_ring_sync,
    )
    from distributed_llama_multiusers_tpu.parallel.comm_stats import collective_stats_of

    prev = ring_sync_enabled()
    try:
        set_ring_sync(True)
        fwd, params, tokens, positions, cache = _q80_sync_fixture()
        ref, _ = fwd(False)(params, tokens, positions, cache)
        got, _ = fwd(True)(params, tokens, positions, cache)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0.15, rtol=0.05)
        assert not np.allclose(np.asarray(got), np.asarray(ref)), (
            "q80 wire produced bit-identical logits — quantized sync not active?"
        )

        base = collective_stats_of(fwd(False), params, tokens, positions, cache)
        q80 = collective_stats_of(fwd(True), params, tokens, positions, cache)
        # ring lowering: hops only — no all-reduce/all-gather ops remain
        for stats in (base, q80):
            assert set(stats["bytes_by_kind"]) == {"collective-permute"}, stats
        # int8 wire on the gather hops: strictly fewer payload bytes than
        # the f32 wire (scales ride too, so the drop is < 4x, but real)
        assert q80["total_bytes"] < base["total_bytes"], (base, q80)
    finally:
        set_ring_sync(prev)


def test_pad_packed_d_out_caps_overhead():
    """Padding to wide slabs is only worth it when cheap: vocab-like widths
    (128256 -> 131072, +2.2%) pad; unlucky widths whose next 8192 multiple
    nearly doubles the bytes (8320 -> 16384) keep their natural layout and
    take the narrow-tile/XLA path instead (round-4 advisor finding)."""
    import numpy as np

    from distributed_llama_multiusers_tpu.quants.packed import (
        PAD_MAX_OVERHEAD, pad_packed_d_out,
    )

    def fake(d_out, d_in=64):
        packed = np.zeros((d_in // 2, d_out), np.uint8)
        scales = np.zeros((d_in // 32, d_out), np.float16)
        return packed, scales

    pk, sc = pad_packed_d_out(*fake(128256))
    assert pk.shape[-1] == 131072 and sc.shape[-1] == 131072

    pk, sc = pad_packed_d_out(*fake(8320))  # +97% > cap: unchanged
    assert pk.shape[-1] == 8320 and sc.shape[-1] == 8320
    assert 8192 * 2 - 8320 > 8320 * PAD_MAX_OVERHEAD  # the case is real
