"""Stall-free admissions: the fused prefill+decode dispatch
(``engine.decode_prefill_fused``) and its scheduler integration —
admissions ride the live pipelined chain instead of flushing it.

Invariants under test: STREAM IDENTITY under admission churn (fused vs
the synchronous scheduler, greedy AND device-sampled lanes), mid-chunk
cancel and stop-string discard (the junk-KV rules), prefix-cache tail
prefill through the fused step, warmup coverage of the per-bucket fused
family, the pod control-plane replay, and the acceptance criterion:
N staggered admissions into a live pipelined chain complete with
``pipeline_flushes == 0`` and streams byte-identical to the synchronous
scheduler — pinned deterministically on the mocked async engine.
"""

import threading
import time

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.runtime.scheduler import RequestState
from distributed_llama_multiusers_tpu.runtime.engine import (
    DEFAULT_TOPP,
    warmup_engine,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer
from distributed_llama_multiusers_tpu.utils.testing import (
    MockAsyncEngine,
    StubStreamTokenizer,
)


@pytest.fixture(scope="module")
def loaded(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    return config, params, tok


def _fresh_engine(config, params, n_lanes=2, **kw):
    return InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(4,), **kw
    )


# ---------------------------------------------------------------------------
# engine level: one fused dispatch == prefill_chunk + pipelined decode step
# ---------------------------------------------------------------------------


def test_engine_fused_step_matches_unfused(loaded):
    """The fused program's decode half emits exactly the pipelined chain's
    tokens for the generating lane (greedy) AND the sampled admitting
    lane's boundary token equals ``prefill_chunk``'s; after the final
    chunk the admitted lane continues from the on-device carry with the
    same stream the synchronous engine produces."""
    config, params, _ = loaded
    prompt0, prompt1 = [5, 9, 3], [7, 2, 8, 1]  # prompt1 = one 4-bucket chunk
    seq_len = config.seq_len
    temps = np.asarray([0.0, 0.8], np.float32)
    topps = np.full(2, DEFAULT_TOPP, np.float32)
    seeds = np.asarray([0, 123], np.uint32)

    # reference: plain synchronous decode of both lanes after sync prefills
    ref = _fresh_engine(config, params)
    _, g0, pos0 = ref.prefill(0, prompt0)
    _, g1, s1 = ref.prefill_chunk(
        1, prompt1, 0, temp=0.8, topp=DEFAULT_TOPP, seed=123
    )
    ref_stream = {0: [int(g0)], 1: [int(s1)]}
    toks = np.asarray([g0, s1], np.int32)
    poss = np.asarray([pos0, len(prompt1)], np.int32)
    for _ in range(4):
        _, greedy, sampled = ref.decode(toks, poss, temps, topps, seeds)
        toks = np.where(temps == 0.0, greedy, sampled).astype(np.int32)
        poss = poss + 1
        ref_stream[0].append(int(toks[0]))
        ref_stream[1].append(int(toks[1]))

    # fused: lane 0 decodes through a pipelined chain; lane 1's prompt
    # rides a fused dispatch mid-chain, then joins from the device carry
    eng = _fresh_engine(config, params)
    _, f0, fpos = eng.prefill(0, prompt0)
    assert int(f0) == int(g0)
    feed = np.asarray([f0, 0], np.int32)
    positions = np.asarray([fpos, seq_len], np.int32)
    out = {0: [int(f0)], 1: []}

    # dispatch 1: plain pipelined, host-seeded
    eng.decode_pipelined(positions.copy(), temps, topps, seeds, tokens=feed)
    positions[0] += 1
    # dispatch 2: fused — lane 1's whole prompt in one chunk (its decode
    # column parks at seq_len)
    eng.decode_prefill_fused(
        positions.copy(), temps, topps, seeds,
        p_lane=1, chunk=prompt1, p_start=0,
        p_temp=0.8, p_topp=DEFAULT_TOPP, p_seed=123,
    )
    positions[0] += 1
    positions[1] = len(prompt1)  # joined: host metadata knows the prompt len

    # consume dispatch 1 (plain [2, n] pack)
    greedy, sampled = eng.pipeline_consume()
    assert greedy.shape[-1] == 2
    out[0].append(int(greedy[0]))

    # two more plain dispatches with lane 1 riding the carry
    for _ in range(2):
        eng.decode_pipelined(positions.copy(), temps, topps, seeds)
        positions = positions + 1
        g, s = eng.pipeline_consume()
        if g.shape[-1] == 3:  # the fused step's pack: boundary column last
            out[0].append(int(g[0]))
            out[1].append(int(s[2]))  # sampled boundary (temp 0.8 lane)
        else:
            out[0].append(int(g[0]))
            out[1].append(int(s[1]))
    while eng.pipeline_inflight():
        g, s = eng.pipeline_consume()
        out[0].append(int(g[0]))
        out[1].append(int(s[1]))
    eng.pipeline_flush()

    assert out[0] == ref_stream[0][: len(out[0])]
    assert out[1] == ref_stream[1][: len(out[1])]
    assert len(out[0]) >= 4 and len(out[1]) >= 2
    snap = eng.stats.snapshot()
    assert snap["fused_steps"] == 1
    assert snap["fused_bucket_hist"] == {4: 1}
    assert snap["pipeline_flushes"] == 0


def test_engine_fused_step_validation(loaded):
    config, params, _ = loaded
    eng = _fresh_engine(config, params)
    z = np.zeros(2, np.int32)
    with pytest.raises(ValueError, match="non-empty"):
        eng.decode_prefill_fused(z, chunk=[], tokens=z)
    with pytest.raises(ValueError, match="exceeds bucket"):
        eng.decode_prefill_fused(z, chunk=[1] * 5, tokens=z)
    with pytest.raises(ValueError, match="seq_len"):
        eng.decode_prefill_fused(
            z, chunk=[1], p_start=config.seq_len, tokens=z
        )
    with pytest.raises(RuntimeError, match="carry"):
        eng.decode_prefill_fused(z, chunk=[1])  # no chain seeded


def test_warmup_covers_fused_family(loaded):
    """Satellite: warmup compiles the fused prefill+decode program for
    every prefill bucket (the first admission into a live chain must not
    eat an XLA compile) and restores every counter afterwards."""
    config, params, _ = loaded
    engine = _fresh_engine(config, params)
    warmup_engine(engine, spec=False, multi_step=0)
    assert not engine.pipeline_active
    snap = engine.stats.snapshot()
    assert snap["fused_steps"] == 0 and snap["pipeline_dispatches"] == 0
    assert snap["prefill_tokens"] == 0 and snap["decode_steps"] == 0
    cache_size = getattr(engine._decode_prefill_fn, "_cache_size", None)
    if cache_size is not None:  # jax exposes the jit cache: one per bucket
        assert cache_size() == len(engine.prefill_buckets)


# ---------------------------------------------------------------------------
# scheduler level: stream identity under admission churn
# ---------------------------------------------------------------------------


def _run_sync(config, params, tok, reqs, n_lanes=2, **kw):
    """Reference run: synchronous scheduler, all requests up front."""
    engine = _fresh_engine(config, params, n_lanes=n_lanes)
    kw.setdefault("speculative", False)
    sched = ContinuousBatchingScheduler(
        engine, tok, prefix_min_tokens=0, multi_step=0,
        pipelined=False, **kw,
    )
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated_tokens) for r in reqs], engine.stats.snapshot()


def _run_churn(config, params, tok, reqs, n_lanes=2, fused=True,
               first_tokens=2, **kw):
    """Churn run: submit the first request, wait until it is demonstrably
    generating (>= first_tokens consumed — with fused on that means the
    pipelined chain is live), then submit the rest one by one."""
    engine = _fresh_engine(config, params, n_lanes=n_lanes)
    kw.setdefault("speculative", False)
    sched = ContinuousBatchingScheduler(
        engine, tok, prefix_min_tokens=0, multi_step=0,
        pipelined=True, fused_prefill=fused, **kw,
    )
    sched.start()
    try:
        sched.submit(reqs[0])
        deadline = time.monotonic() + 120
        while len(reqs[0].generated_tokens) < first_tokens:
            assert time.monotonic() < deadline, "first request never started"
            time.sleep(0.002)
        for r in reqs[1:]:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated_tokens) for r in reqs], engine.stats.snapshot()


def test_scheduler_fused_admission_stream_identity(loaded):
    """Admissions into a live chain (greedy + seeded device-sampled, more
    requests than lanes so one rides the queue until a lane frees) emit
    byte-identical streams to the synchronous scheduler, with zero
    pipeline flushes — the stall-free admission contract."""
    config, params, tok = loaded

    def reqs():
        return [
            Request(prompt="hello world", max_tokens=24, temperature=0.0),
            Request(prompt="other prompt", max_tokens=16, temperature=0.8,
                    seed=42),
            Request(prompt="third request here", max_tokens=10,
                    temperature=0.0),
        ]

    base, _ = _run_sync(config, params, tok, reqs())
    pl, stats = _run_churn(config, params, tok, reqs())
    assert pl == base
    assert stats["fused_steps"] > 0  # admissions actually rode the chain
    assert stats["pipeline_flushes"] == 0
    assert stats["pipeline_dispatches"] > 0


def test_scheduler_fused_off_escape_hatch(loaded):
    """fused_prefill=False restores the pre-fused behavior: admissions
    flush the chain to the synchronous path — streams still identical."""
    config, params, tok = loaded

    def reqs():
        return [
            Request(prompt="hello world", max_tokens=20, temperature=0.0),
            Request(prompt="other prompt", max_tokens=8, temperature=0.0),
        ]

    base, _ = _run_sync(config, params, tok, reqs())
    pl, stats = _run_churn(config, params, tok, reqs(), fused=False)
    assert pl == base
    assert stats["fused_steps"] == 0
    assert stats["pipeline_flushes"] >= 1  # the admission cut the chain


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_scheduler_fused_stop_string_under_churn(loaded):
    """A stop string firing on a live lane while an admission's chunks are
    in flight: the lagged consume discards the lane's junk steps and both
    streams stay byte-identical to the synchronous scheduler."""
    config, params, tok = loaded
    probe = Request(prompt="hello world", max_tokens=24, temperature=0.0)
    _run_sync(config, params, tok, [probe])
    dec = tok.make_stream_decoder()
    pieces = [dec.decode(t) for t in probe.generated_tokens]
    stop = next(
        (p for i, p in enumerate(pieces)
         if 4 <= i <= len(pieces) - 8 and p and p.strip()),
        None,
    )
    assert stop is not None, f"no usable mid-stream piece in {pieces!r}"

    def reqs():
        return [
            Request(prompt="hello world", max_tokens=24, temperature=0.0,
                    stop=[stop]),
            Request(prompt="other prompt", max_tokens=12, temperature=0.0),
        ]

    base, _ = _run_sync(config, params, tok, reqs())
    pl_reqs = reqs()
    pl, stats = _run_churn(config, params, tok, pl_reqs, first_tokens=2)
    assert pl == base
    assert pl_reqs[0].finish_reason == "stop"
    assert len(pl[0]) < 24  # the stop really fired


def test_scheduler_wide_nucleus_admission_rides_chain(loaded):
    """A wide-nucleus admission (top_p = 1.0 — the old host-exact flush
    class) samples on device with the exact full-vocab sampler now, so
    its chunks ride fused dispatches like any other admission: zero
    flushes, streams identical to the synchronous scheduler."""
    config, params, tok = loaded

    def reqs():
        return [
            Request(prompt="hello world", max_tokens=20, temperature=0.0),
            Request(prompt="other prompt", max_tokens=6, temperature=0.8,
                    topp=1.0, seed=3),  # wide nucleus: on-device exact
        ]

    base, _ = _run_sync(config, params, tok, reqs())
    pl, stats = _run_churn(config, params, tok, reqs())
    assert pl == base
    assert stats["pipeline_flushes"] == 0  # no flush class left for it
    assert stats["host_exact_lanes"] == 0


def test_scheduler_host_sampling_admission_still_flushes(loaded):
    """host_sampling=True is the one admission kind that still exits the
    chain (full logits every step): the chain flushes, the sync path
    serves it bit-exactly, and streams match the synchronous scheduler
    for both lanes."""
    config, params, tok = loaded

    def reqs():
        return [
            Request(prompt="hello world", max_tokens=20, temperature=0.0),
            Request(prompt="other prompt", max_tokens=6, temperature=0.8,
                    topp=0.9, seed=3),  # host Sampler escape hatch
        ]

    base, _ = _run_sync(config, params, tok, reqs(), host_sampling=True)
    pl, stats = _run_churn(config, params, tok, reqs(), host_sampling=True)
    assert pl == base
    assert stats["pipeline_flushes"] >= 1  # the host-exact claim flushed
    assert stats["fused_steps"] == 0  # its chunks went through sync prefill


def test_scheduler_fused_cancel_mid_admission(loaded):
    """A cancel landing while the admission's prompt chunks stream through
    the chain: the request resolves as cancelled, its in-flight junk is
    discarded, and the surviving lane's stream is untouched."""
    config, params, tok = loaded
    solo = Request(prompt="hello world", max_tokens=28, temperature=0.0)
    base, _ = _run_sync(config, params, tok, [solo])

    engine = _fresh_engine(config, params, n_lanes=2)
    sched = ContinuousBatchingScheduler(
        engine, tok, speculative=False, prefix_min_tokens=0, multi_step=0,
        pipelined=True,
    )
    survivor = Request(prompt="hello world", max_tokens=28, temperature=0.0)
    victim = Request(prompt="a much longer prompt that spans several "
                            "prefill buckets for sure", max_tokens=8,
                     temperature=0.0)
    sched.start()
    try:
        sched.submit(survivor)
        deadline = time.monotonic() + 120
        while len(survivor.generated_tokens) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        sched.submit(victim)
        # cancel as soon as the admission has claimed its lane (prompt
        # chunks now ride the chain)
        while victim.state == RequestState.QUEUED:
            assert time.monotonic() < deadline
            time.sleep(0.001)
        victim.cancel()
        survivor.future.result(timeout=300)
        victim.future.result(timeout=300)
    finally:
        sched.stop()
    assert survivor.error is None and victim.error is None
    assert victim.finish_reason == "cancelled"
    assert list(survivor.generated_tokens) == base[0]


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_scheduler_fused_prefix_cache_tail(loaded):
    """Satellite: an admission whose prompt prefix is already resident
    (a finished lane's KV) prefills only the TAIL through the fused step —
    stream identical to the cold run, with a recorded prefix hit."""
    config, params, tok = loaded
    shared = "shared prefix for reuse "

    def run(prefix_min):
        engine = _fresh_engine(config, params, n_lanes=2)
        sched = ContinuousBatchingScheduler(
            engine, tok, speculative=False, prefix_min_tokens=prefix_min,
            multi_step=0, pipelined=True,
        )
        sched.start()
        try:
            # c holds lane 0 for the whole test; a runs and finishes on
            # lane 1, leaving its KV resident there; b then claims lane 1
            # while c still generates — a churn admission whose TAIL
            # prefills through the fused step after the prefix copy
            c = sched.submit(Request(prompt="unrelated words go here",
                                     max_tokens=40))
            deadline = time.monotonic() + 120
            while len(c.generated_tokens) < 2:
                assert time.monotonic() < deadline
                time.sleep(0.002)
            a = sched.submit(Request(prompt=shared, max_tokens=6))
            a.future.result(timeout=300)
            b = sched.submit(Request(prompt=shared, max_tokens=12))
            b.future.result(timeout=300)
            c.future.result(timeout=300)
            assert a.error is None and b.error is None and c.error is None
            snap = engine.stats.snapshot()
            return list(b.generated_tokens), snap
        finally:
            sched.stop()

    cold, _ = run(prefix_min=0)
    warm, snap = run(prefix_min=4)
    assert snap["prefix_hits"] >= 1  # B really reused resident KV
    assert warm == cold


# ---------------------------------------------------------------------------
# mocked async engine: the acceptance criterion, deterministically
# ---------------------------------------------------------------------------


def test_mocked_churn_zero_flushes_and_identity():
    """Acceptance criterion: N staggered admissions into a live pipelined
    chain complete with ``pipeline_flushes == 0`` and output streams
    byte-identical to the synchronous scheduler on the same seeds (the
    mock's tokens are a pure function of (lane, position), so identity is
    exact equality)."""
    N = 8

    def reqs():
        return [
            Request(prompt="churn request text", max_tokens=24,
                    temperature=0.0)
            for _ in range(N)
        ]

    def drive(engine, rs, pipelined, staggered):
        sched = ContinuousBatchingScheduler(
            engine, StubStreamTokenizer(engine.config.vocab_size),
            speculative=False, prefix_min_tokens=0, multi_step=0,
            pipelined=pipelined,
        )
        sched.start()
        try:
            if not staggered:
                for r in rs:
                    sched.submit(r)
            else:
                sched.submit(rs[0])
                deadline = time.monotonic() + 60
                while engine.stats.snapshot()["pipeline_dispatches"] < 3:
                    assert time.monotonic() < deadline, "chain never formed"
                    time.sleep(0.002)
                for r in rs[1:]:
                    sched.submit(r)
                    time.sleep(engine.step_s * 2)
            for r in rs:
                r.future.result(timeout=60)
        finally:
            sched.stop()
        assert all(r.error is None for r in rs), [r.error for r in rs]
        return [list(r.generated_tokens) for r in rs]

    base_engine = MockAsyncEngine(n_lanes=4, max_chunk=4)
    base = drive(base_engine, reqs(), pipelined=False, staggered=False)

    churn_engine = MockAsyncEngine(n_lanes=4, max_chunk=4, step_s=0.003)
    churn_reqs = reqs()
    out = drive(churn_engine, churn_reqs, pipelined=True, staggered=True)

    assert out == base
    snap = churn_engine.stats.snapshot()
    assert snap["pipeline_flushes"] == 0  # no admission ever cut the chain
    assert snap["fused_steps"] >= 2  # admissions really rode fused dispatches
    # the StubStreamTokenizer's 8-token prompts over a 4-token max_chunk
    # exercise multi-chunk fused admission
    assert snap["fused_bucket_hist"].get(4, 0) == snap["fused_steps"]
    assert snap["admission_stall_s"] >= 0.0


def test_mocked_fused_admission_overlap_preserved():
    """The overlap structure survives churn: consumes keep running behind
    younger dispatches while admissions stream through the chain."""
    engine = MockAsyncEngine(n_lanes=2, max_chunk=4, step_s=0.004)
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        speculative=False, prefix_min_tokens=0, multi_step=0,
    )
    first = Request(prompt="aaaa", max_tokens=40, temperature=0.0)
    second = Request(prompt="bbbb", max_tokens=8, temperature=0.0)
    sched.start()
    try:
        sched.submit(first)
        deadline = time.monotonic() + 60
        while engine.stats.snapshot()["pipeline_dispatches"] < 4:
            assert time.monotonic() < deadline, "pipeline never engaged"
            time.sleep(0.002)
        sched.submit(second)
        second.future.result(timeout=60)
        first.future.result(timeout=60)
    finally:
        sched.stop()
    assert first.error is None and second.error is None
    assert len(second.generated_tokens) == 8
    snap = engine.stats.snapshot()
    assert snap["pipeline_flushes"] == 0  # the admission did NOT flush
    assert snap["fused_steps"] >= 1
    consumed, overlapped = engine.count_overlapped_consumes()
    assert consumed >= 40
    assert overlapped >= consumed // 2, engine.events


# ---------------------------------------------------------------------------
# pod control plane: OP_DECODE_PREFILL_FUSED replay
# ---------------------------------------------------------------------------


def test_pod_packet_replays_decode_prefill_fused():
    """The fused packet round-trips the feed flag, ring depth, chunk
    tokens, and the prefill header (lane, start, temp/topp bits, seed)
    into the worker's fused engine call — with the same flush-then-reseed
    and bounded-lag consume rules as OP_DECODE_PIPELINED."""
    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    calls = []

    class _Eng:
        n_lanes = 2
        SPEC_DRAFT = 3
        pipeline_depth = 2

        def __init__(self):
            self._ring = 0

        def pipeline_inflight(self):
            return self._ring

        def pipeline_consume(self):
            calls.append(("consume",))
            self._ring -= 1

        def pipeline_flush(self, count=True):
            assert count is False  # worker flushes never count as aborts
            calls.append(("flush", self._ring))
            self._ring = 0

        def decode_prefill_fused(self, positions, temps=None, topps=None,
                                 seeds=None, p_lane=0, chunk=None,
                                 p_start=0, p_temp=0.0, p_topp=0.9,
                                 p_seed=0, tokens=None, g_states=None,
                                 p_g=0):
            self._ring += 1
            calls.append((
                "fused",
                None if tokens is None else np.asarray(tokens).tolist(),
                np.asarray(positions).tolist(),
                list(chunk), p_lane, p_start,
                round(float(p_temp), 4), round(float(p_topp), 4), p_seed,
            ))

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self):
            super().__init__(n_lanes=2, chunk=8)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    plane = _Plane()
    temps = np.asarray([0.0, 0.8], np.float32)
    topps = np.full(2, 0.9, np.float32)
    seeds = np.asarray([1, 2], np.uint32)
    # host-fed reseed carrying a chunk, then two device-fed fused steps
    plane.send_decode_prefill_fused(
        np.asarray([7, 9], np.int32), np.asarray([3, 4], np.int32),
        temps, topps, seeds, depth=2,
        p_lane=1, chunk=[11, 12, 13], p_start=0,
        p_temp=0.8, p_topp=0.9, p_seed=99,
    )
    for pos, start in (((4, 5), 3), ((5, 6), 6)):
        plane.send_decode_prefill_fused(
            None, np.asarray(pos, np.int32), temps, topps, seeds, depth=2,
            p_lane=1, chunk=[21, 22], p_start=start,
            p_temp=0.8, p_topp=0.9, p_seed=99,
        )
    plane.send_pipeline_flush()
    plane.send_stop()

    replay = iter(sent)

    class _ReplayPlane:
        def recv(self):
            return next(replay)

        def slot(self, pkt, i, n):
            return plane.slot(pkt, i, n)

    mh.worker_loop(_Eng(), _ReplayPlane())
    kinds = [c[0] for c in calls]
    # host-fed -> flush+fused; device-fed -> fused; ring at depth 2 before
    # the third -> consume first; the chain-end flush drains the ring
    assert kinds == ["flush", "fused", "fused", "consume", "fused",
                     "flush"], calls
    first = calls[1]
    assert first[1] == [7, 9] and first[2] == [3, 4]
    assert first[3] == [11, 12, 13] and first[4] == 1 and first[5] == 0
    assert first[6] == 0.8 and first[7] == 0.9 and first[8] == 99
    assert calls[2][1] is None and calls[2][2] == [4, 5]
    assert calls[2][3] == [21, 22] and calls[2][5] == 3
    assert calls[4][5] == 6  # the third chunk's offset rode the header


def test_root_engine_validates_fused_chunk_before_broadcast():
    """A fused chunk that cannot pair with exactly one worker-side compute
    must raise BEFORE any packet goes out (the pod-deadlock rule)."""
    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self):
            super().__init__(n_lanes=2, chunk=8)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    class _Eng:
        n_lanes = 2

        def max_chunk(self):
            return 4

    root = mh.RootControlEngine(_Eng(), _Plane())
    z = np.zeros(2, np.int32)
    with pytest.raises(ValueError, match="outside"):
        root.decode_prefill_fused(z, chunk=[], tokens=z)
    with pytest.raises(ValueError, match="outside"):
        root.decode_prefill_fused(z, chunk=[1] * 5, tokens=z)
    assert sent == []  # nothing was broadcast
