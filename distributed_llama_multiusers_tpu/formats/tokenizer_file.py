"""The `.t` tokenizer file format.

Format (reference src/tokenizer.cpp:42-170, converter/tokenizer-writer.py):

    int32 magic = 0x567124
    int32 headerSize               # 8 + 8*nKv
    (int32 key, int32 value) * nKv
    chat template bytes (if CHAT_TEMPLATE present; value = byte length)
    int32 eosTokenId * N_EOS_TOKENS
    per token: (float32 score, uint32 length, bytes)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import BinaryIO

TOKENIZER_MAGIC = 0x567124

KEY_TOK_VERSION = 0
KEY_TOK_VOCAB_SIZE = 1
KEY_MAX_TOKEN_LENGTH = 2
KEY_BOS_ID = 3
KEY_EOS_ID = 4  # backward compat: appends to eos list
KEY_PAD_ID = 5  # ignored
KEY_CHAT_EOS_ID = 6  # backward compat: appends to eos list
KEY_CHAT_TEMPLATE = 7
KEY_CHAT_STOP = 8  # ignored (value bytes skipped)
KEY_N_EOS_TOKENS = 9


@dataclass
class TokenizerData:
    vocab: list[bytes] = field(default_factory=list)
    scores: list[float] = field(default_factory=list)
    bos_id: int = -1
    eos_token_ids: list[int] = field(default_factory=list)
    chat_template: str | None = None
    max_token_length: int = 0

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)


def write_tokenizer_file(f: BinaryIO, data: TokenizerData) -> None:
    """Mirror of converter/tokenizer-writer.py:3-56."""
    n_tokens = len(data.vocab)
    max_token_length = max(len(t) for t in data.vocab)
    chat_template = data.chat_template.encode("utf-8") if data.chat_template else None

    pairs = [
        (KEY_BOS_ID, data.bos_id),
        (KEY_TOK_VERSION, 1),
        (KEY_TOK_VOCAB_SIZE, n_tokens),
        (KEY_MAX_TOKEN_LENGTH, max_token_length),
    ]
    if chat_template:
        pairs.append((KEY_CHAT_TEMPLATE, len(chat_template)))
    pairs.append((KEY_N_EOS_TOKENS, len(data.eos_token_ids)))

    body = b"".join(struct.pack("<ii", k, v) for k, v in pairs)
    f.write(struct.pack("<i", TOKENIZER_MAGIC))
    f.write(struct.pack("<i", 8 + len(body)))
    f.write(body)
    if chat_template:
        f.write(chat_template)
    for eos in data.eos_token_ids:
        f.write(struct.pack("<i", eos))
    for token, score in zip(data.vocab, data.scores):
        assert len(token) > 0
        f.write(struct.pack("<fI", score, len(token)))
        f.write(token)


def load_tokenizer_file(path: str) -> TokenizerData:
    """Mirror of Tokenizer::Tokenizer (src/tokenizer.cpp:42-170), new format only."""
    data = TokenizerData()
    with open(path, "rb") as f:
        magic = struct.unpack("<i", f.read(4))[0]
        if magic != TOKENIZER_MAGIC:
            raise ValueError("Invalid tokenizer file (old 0x567123 format not supported)")
        header_size = struct.unpack("<i", f.read(4))[0]
        n_kv = (header_size - 8) // 8
        buf = f.read(n_kv * 8)
        version = -1
        chat_template_length = -1
        n_eos_tokens = 0
        vocab_size = 0
        skip_after_header = 0
        for i in range(n_kv):
            key, value = struct.unpack_from("<ii", buf, i * 8)
            if key == KEY_TOK_VERSION:
                version = value
            elif key == KEY_TOK_VOCAB_SIZE:
                vocab_size = value
            elif key == KEY_MAX_TOKEN_LENGTH:
                data.max_token_length = value
            elif key == KEY_BOS_ID:
                data.bos_id = value
            elif key in (KEY_EOS_ID, KEY_CHAT_EOS_ID):
                data.eos_token_ids.append(value)
            elif key == KEY_CHAT_TEMPLATE:
                chat_template_length = value
            elif key == KEY_CHAT_STOP:
                skip_after_header += value
            elif key == KEY_PAD_ID:
                pass
            elif key == KEY_N_EOS_TOKENS:
                n_eos_tokens = value
            else:
                raise ValueError(f"Invalid tokenizer header key: {key}")
        if version != 1:
            raise ValueError("Old tokenizer version, please regenerate your tokenizer")
        if skip_after_header:
            f.read(skip_after_header)
        if chat_template_length > 0:
            data.chat_template = f.read(chat_template_length).decode("utf-8")
        for _ in range(n_eos_tokens):
            data.eos_token_ids.append(struct.unpack("<i", f.read(4))[0])
        for _ in range(vocab_size):
            score, length = struct.unpack("<fI", f.read(8))
            data.vocab.append(f.read(length))
            data.scores.append(score)
        if data.max_token_length < 1:
            raise ValueError("Invalid tokenizer max token length")
    return data
