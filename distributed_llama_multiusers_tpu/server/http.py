"""Multi-user HTTP API server.

Routes match the reference's dllama-api (src/dllama-api.cpp:338-349):
POST /v1/chat/completions and GET /v1/models, with CORS preflight —
plus, beyond parity: POST /v1/completions (raw-prompt text completion,
no chat template), GET /stats, and GET /health.

Concurrency model is where this departs from the fork: the fork accepts one
connection at a time and blocks the accept loop on future.get()
(dllama-api.cpp:250-288,351-365), so despite its batching loop only one HTTP
request is ever in flight. Here a ThreadingHTTPServer gives every connection
its own thread; all of them submit into the shared RequestQueue and their
generations proceed concurrently in the continuous batch. SSE streaming
(``"stream": true``) is supported — upstream shipped the chunk types but
never wired them (api-types.hpp:45-57).

Observability surface (telemetry/, docs/OBSERVABILITY.md): ``GET /metrics``
serves Prometheus text bridged from the same snapshot ``GET /stats``
returns (the two reconcile by construction), ``GET /trace`` serves the
span ring as Perfetto-loadable Chrome trace JSON, completion responses
carry the per-request summary (ttft_s, tbt p50/p95, queued_s, ...), and
every error payload — 400/500 JSON and mid-stream SSE error events —
names the ``request_id``, so a streamed failure correlates with the
server's per-request JSON log line.

Resumable SSE (crash-durable serving, docs/SERVING.md): every streamed
delta carries its token index as the SSE ``id:`` line; with
``--reconnect-grace`` > 0 a disconnected client reattaches within the
window via ``GET /v1/stream/<request_id>`` + ``Last-Event-ID`` — to the
live request (which kept generating into its bounded relay) or to one
recovered from the request journal after a crash — and the stream
resumes byte-identically. All shed Retry-After hints (queue full,
breaker open, stalled-503) carry deterministic ±20% per-request jitter
so a shed burst's synchronized retries cannot thundering-herd a
recovering replica.
"""

from __future__ import annotations

import itertools
import json
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..analysis import leakcheck
from ..runtime.scheduler import Request, fresh_request_id
from ..telemetry.tracectx import TRACE_HEADER, TraceContext
from ..serving import (
    AdmissionRejected,
    StreamRelay,
    attach_recovered_stream,
    entry_from_admit_record,
    jittered_retry_after,
)
from ..tokenizer import ChatItem, TemplateType, chat_generator_for
from . import api_types

# defense-in-depth bound on how long an HTTP thread waits on the
# scheduler (seconds). GENEROUS by design: the scheduler's own deadlines
# (queue timeout, generation budget) and the failure-containment layer
# resolve futures long before this; the bound only exists so a wedged
# scheduler — the failure mode the watchdog detects but cannot unblock —
# can never hang a client socket forever.
DEFAULT_RESULT_TIMEOUT_S = 600.0

# Retry-After jitter keys for sheds with no request yet (a draining
# submit that failed before a Request existed): a distinct key per shed
# keeps even those spread across the ±20% band (serving/qos.py)
_shed_keys = itertools.count(1)


class SchedulerStalled(RuntimeError):
    """A request's future made no progress within the server-side wait
    bound: the scheduler is wedged (or the request leaked). Mapped to a
    request_id-carrying 503 + Retry-After — retryable, because a restart
    or the watchdog will have replaced the engine by then."""

    def __init__(self, request_id: int, waited_s: float):
        self.request_id = request_id
        super().__init__(
            f"no scheduler progress on request {request_id} within "
            f"{waited_s:.0f}s; the server is unhealthy — retry elsewhere"
        )


class ApiServer:
    def __init__(self, scheduler, tokenizer, model_name: str = "dllama",
                 template_type: TemplateType = TemplateType.UNKNOWN,
                 result_timeout_s: float = DEFAULT_RESULT_TIMEOUT_S,
                 resume=None, replica_id: str | None = None,
                 role: str = "mixed"):
        """``resume`` (serving/resume.StreamRegistry, built by dllama-api
        when ``--reconnect-grace`` > 0): streamed requests register their
        delta relay so a disconnected client can reattach within the
        grace window (``GET /v1/stream/<id>`` + ``Last-Event-ID``) —
        including streams recovered from the journal after a crash. None
        (the default) preserves cancel-on-disconnect exactly.

        ``replica_id`` (``--replica-id``, default host:port at
        ``serve()``): this replica's name in a fleet — stamped as the
        ``X-DLlama-Replica`` header on every response and onto the SSE
        terminal chunk, so fleet traces and the migration path can
        attribute every shed and every stream to its source replica.

        ``role`` (``--role``, default ``"mixed"``): this replica's
        disaggregation role — ``"prefill"`` replicas take long-prompt
        traffic and hand sessions off after first token,
        ``"decode"``/``"mixed"`` replicas take the decode side.
        Surfaced on ``GET /load`` so the router's scrape learns the
        fleet topology instead of being configured twice."""
        self.scheduler = scheduler
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.chat_template = chat_generator_for(tokenizer, template_type)
        self.result_timeout_s = result_timeout_s
        self.resume = resume
        self.replica_id = replica_id
        self.role = str(role or "mixed")
        self._httpd: ThreadingHTTPServer | None = None
        self._fallback_tel = None  # see _telemetry()

    # -- request handling ---------------------------------------------------

    def _make_request(self, prompt: str, body: dict, streaming: bool,
                      kind: str | None = None,
                      trace: str | None = None) -> tuple[Request, StreamRelay | None]:
        """Shared Request construction for both routes (one place owns the
        body->Request field mapping). Streaming requests get a
        :class:`~..serving.resume.StreamRelay`: every delta is buffered
        with its TOKEN INDEX (the SSE ``id:`` line), which is what makes
        a stream resumable — the pump and any reconnecting client
        address the stream by index, not by socket position.

        ``trace`` is the validated ``X-DLlama-Trace`` wire context (or
        None): stamped onto the Request, so every span this request emits
        carries the fleet-wide trace id and the admit journal record
        (hence migration tickets and crash recovery) re-joins the trace."""
        params = api_types.InferenceParams.from_body(body)
        req = Request(
            prompt=prompt,
            max_tokens=params.max_tokens,
            temperature=params.temperature,
            topp=params.top_p,
            seed=params.seed,
            stop=params.stop,
            user_id=params.user,
            priority=params.priority,
            response_format=params.response_format,
            api_kind=kind,
            trace=trace,
        )
        relay = None
        if streaming:
            if self.resume is not None:
                relay = self.resume.register(req, kind=kind)
            else:
                # no reconnect semantics: unbounded (capacity 0), the
                # pre-resume delta queue's exact behavior — a slow but
                # connected client backpressures into memory, nothing
                # is ever evicted out from under it
                relay = StreamRelay(req.id, capacity=0)
                req.future.add_done_callback(lambda _f: relay.finish())
            # on_delta runs on the scheduler thread right after the token
            # was consumed, so len(generated_tokens) IS the delta's
            # token index
            req.on_delta = lambda d: relay.push(len(req.generated_tokens), d)
        return req, relay

    def build_request(self, body: dict, streaming: bool,
                      trace: str | None = None) -> tuple[Request, StreamRelay | None]:
        """Validate the body and build the Request. Raises ValueError on bad
        input — callers must do this BEFORE committing response headers."""
        messages = api_types.parse_chat_messages(body)
        chat = self.chat_template.generate(
            [ChatItem(m.role, m.content) for m in messages], append_generation_prompt=True
        )
        return self._make_request(chat.content, body, streaming, kind="chat",
                                  trace=trace)

    def build_completion_request(self, body: dict, streaming: bool,
                                 trace: str | None = None) -> tuple[Request, StreamRelay | None]:
        """/v1/completions: the raw prompt goes straight to the scheduler —
        no chat template. Beyond reference parity (the fork serves only
        the chat route, src/dllama-api.cpp:338-349)."""
        prompt = api_types.parse_completion_prompt(body)
        return self._make_request(prompt, body, streaming, kind="completion",
                                  trace=trace)

    def handle_chat_completion(self, body: dict, send_chunk=None, prepared=None) -> dict:
        """Run a (pre-validated) request through the shared batching loop.
        If send_chunk is given, stream deltas through it."""
        req, deltas = prepared if prepared is not None else self.build_request(body, send_chunk is not None)
        return self._run_request(
            req, deltas, send_chunk,
            api_types.chat_chunk_response, api_types.chat_completion_response,
        )

    def handle_completion(self, body: dict, send_chunk=None, prepared=None) -> dict:
        req, deltas = prepared if prepared is not None else self.build_completion_request(body, send_chunk is not None)
        return self._run_request(
            req, deltas, send_chunk,
            api_types.completion_chunk_response, api_types.completion_response,
        )

    def _run_request(self, req, relay, send_chunk, chunk_fn, response_fn) -> dict:
        if req.submitted_at is None:  # streaming pre-submits before headers
            self.scheduler.submit(req)

        if send_chunk:
            try:
                self._pump(req, relay, relay.attach(), 0, send_chunk,
                           chunk_fn)
            except (BrokenPipeError, ConnectionError, OSError):
                if self.resume is not None:
                    # reconnect-grace window: the request KEEPS generating
                    # into its bounded relay; a client reattaching with
                    # Last-Event-ID (GET /v1/stream/<id>) resumes
                    # mid-stream, and the registry reaper cancels on
                    # grace expiry if nobody returns
                    self.resume.detach(req.id)
                else:
                    # default (grace 0): free the lane instead of
                    # generating to max_tokens into an orphaned buffer
                    req.cancel()
                raise
            return {}

        try:
            # satellite (failure containment): a generous bound so a wedged
            # scheduler can never hang a client socket forever — mapped to
            # a request_id-carrying 503 by the route handler
            text = req.future.result(timeout=self.result_timeout_s)
        except FutureTimeout:
            req.cancel()  # frees the lane if the loop ever recovers
            raise SchedulerStalled(req.id, self.result_timeout_s) from None
        return response_fn(
            self.model_name, req.id, text, req.n_prompt_tokens, len(req.generated_tokens),
            req.finish_reason or "stop", summary=req.summary,
        )

    def _pump(self, req, relay, gen, after, send_chunk, chunk_fn) -> bool:
        """Drain a stream's relay to one SSE consumer, starting after
        token index ``after`` (0 for a fresh stream, the client's
        Last-Event-ID on a reconnect). Every delta goes out with its
        token index as the SSE ``id:`` line and — once it has reached
        the client transport — advances the journal's delivery
        watermark, so a crash recovers to a point the client had
        actually seen. Returns True when the terminal chunk went out,
        False on a quiet end (superseded by a newer consumer, or a
        resume gap the client must restart from)."""
        journal = getattr(self.scheduler, "journal", None)
        while True:
            item = relay.next_after(after, timeout=self.result_timeout_s,
                                    gen=gen)
            if item is None:
                # bounded like the non-streaming wait: the gap between
                # deltas is the streaming liveness signal, and a wedged
                # scheduler must become a terminal error chunk, not a
                # socket held open forever
                req.cancel()
                raise SchedulerStalled(req.id, self.result_timeout_s)
            tag = item[0]
            if tag == "delta":
                _, idx, text = item
                send_chunk(
                    chunk_fn(self.model_name, req.id, text, False),
                    event_id=idx,
                )
                after = idx
                if journal is not None:
                    # watermark AFTER the chunk reached the transport
                    # (a diagnostics floor — recovery never discards by
                    # it, since a socket write is not client receipt)
                    journal.note_progress(req.id, idx)
                continue
            if tag == "superseded":
                return False  # a reconnect took the stream over; unwind
            if tag == "gap":
                # deltas past this consumer's position were evicted from
                # the bounded buffer: byte-identical resumption is
                # impossible — fail closed rather than silently skip
                send_chunk({
                    "error": "resume window exceeded; restart the request",
                    "reason": "resume_gap", "request_id": req.id,
                })
                if self.resume is not None:
                    # a client that closes cleanly after this error chunk
                    # raises no socket exception, so nothing else would
                    # start the grace clock — without this the request
                    # generates to max_tokens for nobody and its entry
                    # only clears at natural finish plus a grace window
                    self.resume.detach(req.id)
                return False
            break  # ("done",): the future resolved
        try:
            req.future.result()  # re-raise failures
        except AdmissionRejected as e:
            # shed after the SSE headers were committed (drain flush, or
            # the paged pool's post-submit pool_exhausted) — too late
            # for the 429/503 status line, so the typed shed ships as an
            # error chunk first: reason + Retry-After hint, or a stream
            # client reads the empty "cancelled" terminal as the model's
            # answer and never backs off or retries
            shed = {
                "error": str(e), "reason": e.reason,
                "retry_after_s": round(
                    jittered_retry_after(e.retry_after_s, req.id), 2
                ),
                "request_id": req.id,
            }
            if self.replica_id:
                shed["replica"] = self.replica_id
            send_chunk(shed)
            req.finish_reason = "cancelled"
        # terminal chunk carries the SAME per-request summary the
        # non-streaming response does (one producer: the scheduler's
        # telemetry finish hook), so stream clients are not blind —
        # plus the replica id, so fleet traces can attribute the stream
        # (and a router can name the source on migration) even when the
        # response headers were consumed by an intermediary
        term = chunk_fn(
            self.model_name, req.id, None, True,
            req.finish_reason or "stop", summary=req.summary,
        )
        if self.replica_id:
            term["replica"] = self.replica_id
        send_chunk(term, event_id=len(req.generated_tokens))
        return True

    def handle_models(self) -> dict:
        return api_types.models_response(self.model_name)

    def handle_stats(self) -> dict:
        """Serving metrics (beyond reference parity — SURVEY §5.5 notes it
        has no metrics endpoint): engine counters plus scheduler occupancy
        and QoS state. Engine counters come from ONE locked snapshot, not
        field-by-field reads racing the batching thread."""
        sched = self.scheduler
        stats = sched.engine.stats.snapshot()
        busy, total = sched.occupancy()
        out = {
            "prefill_tokens": stats["prefill_tokens"],
            "prefill_s": round(stats["prefill_s"], 3),
            "decode_steps": stats["decode_steps"],
            "decode_s": round(stats["decode_s"], 3),
            "host_bytes_in": stats["host_bytes_in"],
            "spec_steps": stats["spec_steps"],
            "spec_emitted": stats["spec_emitted"],
            "spec_lane_steps": stats["spec_lane_steps"],
            # acceptance per (DRAFTED lane, verify-step): 1.0 = no draft
            # accepted, K+1 = full acceptance. Sampled/draft-less lanes ride
            # the same batched call but are excluded from both counters.
            "spec_tokens_per_lane_step": (
                round(stats["spec_emitted"] / stats["spec_lane_steps"], 3)
                if stats["spec_lane_steps"] else None
            ),
            # zero-flush serving: spec verify steps dispatched INSIDE the
            # pipelined ring, the device accept-count histogram (drafted
            # lanes only; 0 = nothing survived the carry-alignment gate,
            # SPEC_DRAFT = full acceptance), and lanes routed through the
            # host Sampler (host_sampling=True only — 0 in default
            # serving, where the on-device sampler is full-vocab exact).
            # /metrics carries dllama_spec_accepted_total delta-fed from
            # the spec_emitted field (telemetry/hub.bridge_stats).
            "spec_pipelined_steps": stats["spec_pipelined_steps"],
            "spec_accept_hist": {
                str(k): v
                for k, v in sorted(stats["spec_accept_hist"].items())
            },
            "host_exact_lanes": stats["host_exact_lanes"],
            # per-step collective traffic (mesh runs; 0 single-chip): the
            # static per-decode estimate, the collective count behind it,
            # and the cumulative payload accrued per decode-family
            # dispatch — the /metrics dllama_sync_bytes_total counter is
            # delta-fed from the same field (telemetry/hub.bridge_stats)
            "sync_bytes_per_decode": stats["sync_bytes_per_decode"],
            "sync_collectives_per_decode": stats["sync_collectives_per_decode"],
            "sync_bytes_total": stats["sync_bytes_total"],
            # multi-step horizons taken (each = several decode steps in one
            # device dispatch; decode_steps counts the chained steps)
            "multi_dispatches": stats["multi_dispatches"],
            # async decode pipeline: host consume time hidden behind device
            # execution, steps dispatched device-fed, chains aborted before
            # their lanes finished, and ring occupancy right after each
            # dispatch (how deep the overlap actually ran)
            "overlap_s": round(stats["overlap_s"], 3),
            "pipeline_dispatches": stats["pipeline_dispatches"],
            "pipeline_flushes": stats["pipeline_flushes"],
            "pipeline_depth_hist": {
                str(k): v
                for k, v in sorted(stats["pipeline_depth_hist"].items())
            },
            # stall-free admissions: fused prefill+decode dispatches taken
            # (admissions riding the live chain), host time decode lanes
            # spent stalled behind admission work, and which prefill
            # buckets the fused dispatches carried
            "fused_steps": stats["fused_steps"],
            "admission_stall_s": round(stats["admission_stall_s"], 3),
            "fused_bucket_hist": {
                str(k): v
                for k, v in sorted(stats["fused_bucket_hist"].items())
            },
            "prefix_hits": stats["prefix_hits"],
            "prefix_tokens_saved": stats["prefix_tokens_saved"],
            # grammar-constrained decoding (grammar/): admissions that
            # attached a compiled automaton and dispatches that carried
            # at least one constrained lane; the slab-pressure gauges
            # (schemas installed/live, state occupancy) ride qos_stats
            "grammar_lanes": stats["grammar_lanes"],
            "grammar_masked_steps": stats["grammar_masked_steps"],
            # failure containment (multihost.worker_serve): supervised
            # restarts + classified protocol errors on THIS process —
            # non-zero only on pod processes that actually restarted
            "worker_restarts": stats["worker_restarts"],
            "worker_replay_errors": stats["worker_replay_errors"],
            # compile stability (analysis/jitcheck.py): XLA compiles
            # observed after warmup armed the recompile witness — MUST
            # read 0 in steady serving (one compiled program per
            # (family, bucket), compiled only at warmup); /metrics
            # carries the dllama_stats_* gauge plus the delta-fed
            # dllama_jit_compiles_total counter (telemetry/hub)
            "jit_compiles_after_warmup": stats["jit_compiles_after_warmup"],
            "lanes_total": total,
            "lanes_busy": busy,
        }
        # resource lifecycles (analysis/leakcheck.py): the process-wide
        # witness counters — resources found held at drain points (MUST
        # read 0, the leak twin of jit_compiles_after_warmup) — plus
        # this scheduler's LIVE ownership gauge (busy serving holds
        # pages/tickets/marks legitimately; only drain points assert
        # zero). bridge_stats republishes resources_live as a labelled
        # gauge and delta-feeds dllama_resource_leaks_total (telemetry/hub)
        out.update(leakcheck.stats())
        # dequant path attribution (ops/dequant_select.py): the configured
        # DLLAMA_DEQUANT knob, and — under auto — the per-(d_in, d_out,
        # m-class) modes resolved at warmup trace time plus the selection
        # table's provenance, so a /stats snapshot pins WHICH kernel chain
        # produced the throughput it reports
        from ..ops.dequant_select import dequant_stats

        out.update(dequant_stats())
        leak_counts = getattr(sched, "leak_counts", None)
        if callable(leak_counts):
            out["resources_live"] = leak_counts()
        qos = getattr(sched, "qos_stats", None)
        if callable(qos):  # queue depth/wait/rejections, timeouts, drain
            out.update(qos())
        if self.resume is not None:  # SSE reattach registry (resume.py)
            out.update(self.resume.stats())
        tel = self._telemetry()
        if tel is not None:  # ring occupancy/eviction: a truncated /trace
            out.update(tel.tracer.counts())  # window is visible, not silent
        return out

    def handle_load(self) -> dict:
        """The fleet routing surface (``GET /load``; the same fields ride
        the ``/health`` body): ONE cheap JSON with everything a router
        needs per routing decision — queue depth, free lanes, paged-pool
        pressure, breaker state, draining flag — so a fleet front-end
        never has to parse full Prometheus text to pick a replica.
        Always HTTP 200 (it is a machine surface, not a readiness
        probe; ``/health`` keeps the status-code semantics)."""
        sched = self.scheduler
        busy, total = sched.occupancy()
        breaker = getattr(sched, "breaker", None)
        depth_fn = getattr(sched.queue, "depth", None)
        draining = bool(getattr(sched, "draining", False))
        br_state = breaker.state if breaker is not None else "closed"
        out = {
            "status": (
                "draining" if draining
                else ("unhealthy" if br_state != "closed" else "ok")
            ),
            "replica": self.replica_id,
            "model": self.model_name,
            "role": self.role,
            "queue_depth": int(depth_fn()) if callable(depth_fn) else 0,
            "lanes_free": total - busy,
            "lanes_total": total,
            "breaker": br_state,
            "draining": draining,
        }
        pool = getattr(sched.engine, "pool_stats", None)
        ps = pool() if callable(pool) else {}
        if ps:  # paged engines only — contiguous ones OMIT the fields
            # (a literal 0 pages free would read as a full pool)
            out["pool_pages_free"] = ps.get("pool_pages_free", 0)
            out["pool_pages_total"] = ps.get("pool_pages_total", 0)
            out["pool_parked_pages"] = ps.get("pool_parked_pages", 0)
        # clock-offset anchor for the fleet trace merge: this replica's
        # CURRENT position on its /trace timebase (µs since the span
        # tracer's perf_counter origin — the same rebasing chrome_trace
        # applies). The router brackets the scrape with its own clock and
        # estimates offset = local_midpoint − this stamp, uncertainty =
        # RTT/2; perf_counter origins are per-process, so there is no
        # cross-host clock to read directly.
        out["trace_clock_us"] = round(
            (time.perf_counter() - self._telemetry().tracer.origin) * 1e6, 1
        )
        return out

    def _telemetry(self):
        """The scheduler's telemetry hub (telemetry/), or a lazily built
        standalone one for custom schedulers without it — /metrics then
        still serves the bridged /stats gauges."""
        tel = getattr(self.scheduler, "telemetry", None)
        if tel is None:
            if self._fallback_tel is None:
                from ..telemetry import Telemetry

                self._fallback_tel = Telemetry()
            tel = self._fallback_tel
        return tel

    def handle_metrics(self) -> str:
        """Prometheus text exposition: the native latency histograms and
        request counters plus every /stats field bridged as a
        ``dllama_stats_*`` gauge — sampled from the same snapshot, so the
        two endpoints reconcile (docs/OBSERVABILITY.md)."""
        return self._telemetry().render_prometheus(bridge=self.handle_stats())

    def handle_trace(self, since: int = 0,
                     trace_id: str | None = None) -> dict:
        """The span ring as Chrome trace-event JSON (Perfetto loadable).

        ``since`` (the doc's top-level ``cursor`` from a prior pull)
        returns only newer events — incremental polling instead of
        re-downloading the whole ring; ``trace_id`` returns only the
        events of one fleet trace (what the router's cross-replica merge
        pulls per replica)."""
        return self._telemetry().chrome_trace(since=since, trace_id=trace_id)

    # -- plumbing -----------------------------------------------------------

    def serve(self, host: str = "0.0.0.0", port: int = 9990) -> ThreadingHTTPServer:
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _cors(self):
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header("Access-Control-Allow-Methods", "GET, POST, OPTIONS")
                self.send_header("Access-Control-Allow-Headers", "Content-Type, Authorization")

            def _json(self, code: int, payload: dict, headers: dict | None = None):
                self._raw(
                    code, json.dumps(payload).encode(), "application/json",
                    headers,
                )

            def _raw(self, code: int, data: bytes, content_type: str,
                     headers: dict | None = None):
                self.send_response(code)
                self._cors()
                if api.replica_id:
                    # fleet attribution: every response names its source
                    # replica, so router traces and migration decisions
                    # can attribute sheds/errors without guessing
                    self.send_header("X-DLlama-Replica", api.replica_id)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _reject(self, e: AdmissionRejected, key: int | None = None):
                # load shed: 429 (queue full) / 503 (draining/breaker),
                # with a Retry-After hint so well-behaved clients back
                # off — jittered ±20% per request (serving/qos.py) so a
                # shed burst's synchronized retries don't thundering-herd
                # the replica the moment it recovers
                retry = jittered_retry_after(
                    e.retry_after_s, key if key is not None else next(_shed_keys)
                )
                self._json(
                    e.http_status,
                    {"error": str(e), "reason": e.reason},
                    headers={"Retry-After": str(max(1, round(retry)))},
                )

            def _sse_headers(self, request_id: int | None = None):
                self.send_response(200)
                self._cors()
                if api.replica_id:
                    self.send_header("X-DLlama-Replica", api.replica_id)
                if request_id is not None:
                    # names the stream BEFORE any delta payload does: a
                    # fleet router fetches its migration ticket
                    # (/admin/session/<id>) off this, so a stream that
                    # dies before its first delta is still migratable
                    self.send_header("X-DLlama-Request", str(request_id))
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()

            def _sse_chunk(self, payload: dict, event_id=None):
                # the `id:` line is the delta's TOKEN INDEX — what a
                # reconnecting client echoes back as Last-Event-ID to
                # resume the stream exactly where it left off
                buf = b""
                if event_id is not None:
                    buf += f"id: {event_id}\n".encode()
                buf += b"data: " + json.dumps(payload).encode() + b"\n\n"
                self.wfile.write(buf)
                self.wfile.flush()

            def do_OPTIONS(self):  # CORS preflight (dllama-api.cpp:228-236)
                self.send_response(204)
                self._cors()
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                if self.path == "/v1/models":
                    self._json(200, api.handle_models())
                elif self.path.startswith("/v1/stream/"):
                    # resumable SSE (serving/resume.py): reattach to a
                    # live or journal-recovered stream by request id,
                    # replaying from the client's Last-Event-ID
                    self._resume_stream()
                elif self.path == "/load":
                    # fleet routing surface: one cheap JSON per routing
                    # decision (queue depth, free lanes, pool pressure,
                    # breaker, draining) — always 200, the router reads
                    # the fields, not the status line
                    self._json(200, api.handle_load())
                elif self.path.startswith("/admin/session/"):
                    # fleet migration ticket: a live session's admit wire
                    # record (resolved seed included) + watermark, for a
                    # router to hand to another replica's /admin/migrate
                    self._export_session()
                elif self.path.startswith("/admin/kvpages/"):
                    # disaggregated prefill: a live session's committed
                    # KV-page bundle (disagg/kvtransfer.py), for a router
                    # to push to a decode replica's /admin/kvimport
                    self._export_pages()
                elif self.path == "/stats":
                    self._json(200, api.handle_stats())
                elif self.path == "/metrics":
                    # Prometheus text exposition format (the version the
                    # format spec names; scrapers key on it)
                    self._raw(
                        200, api.handle_metrics().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path.split("?", 1)[0] == "/trace":
                    # Chrome trace-event JSON: save and load in Perfetto.
                    # ?since=<cursor> returns only newer events (the
                    # response's top-level `cursor` is the resume point);
                    # ?trace_id=<32-hex> filters to one fleet trace (what
                    # the router's /trace/<id> merge pulls per replica)
                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        since = int(q.get("since", ["0"])[0])
                    except ValueError:
                        self._json(400, {"error": "bad since cursor"})
                        return
                    trace_id = q.get("trace_id", [None])[0]
                    self._json(200, api.handle_trace(
                        since=since, trace_id=trace_id,
                    ))
                elif self.path in ("/", "/health"):
                    # readiness: flips to 503 during drain so load balancers
                    # stop routing here while in-flight work finishes — and
                    # while the engine circuit breaker is open/half-open
                    # (serving/breaker.py: repeated engine failures or a
                    # watchdog-detected stall), so a failing replica stops
                    # taking traffic instead of collecting hung clients
                    # the body is handle_load()'s full machine surface
                    # (queue depth, free lanes, pool pressure, breaker,
                    # draining) so a router scraping /health per routing
                    # decision gets everything in one parse; the status
                    # CODE keeps the load-balancer readiness semantics
                    breaker = getattr(api.scheduler, "breaker", None)
                    load = api.handle_load()
                    if load["draining"]:
                        self._json(503, load, headers={"Retry-After": "5"})
                    elif load["breaker"] != "closed":
                        self._json(
                            503, load,
                            headers={
                                "Retry-After": str(
                                    max(1, round(breaker.retry_after_s()))
                                )
                            },
                        )
                    else:
                        self._json(200, load)
                else:
                    self._json(404, {"error": "not found"})

            def _export_session(self):
                """``GET /admin/session/<request_id>``: export a live
                session's migration ticket — the admit wire record
                (prompt tokens + RESOLVED seed + params) plus the
                consumed-token watermark. 404 for unknown/finished
                requests and for schedulers without the export surface.
                The router caches this at stream start so a replica
                death can still be migrated after the source is gone."""
                try:
                    rid = int(self.path.rsplit("/", 1)[1])
                except ValueError:
                    self._json(400, {"error": "bad session id"})
                    return
                export = getattr(api.scheduler, "export_session", None)
                rec = export(rid) if callable(export) else None
                if rec is None:
                    self._json(404, {
                        "error": "unknown or finished session "
                                 "(only admitted, in-flight requests "
                                 "export a migration ticket)",
                        "request_id": rid,
                    })
                    return
                self._json(200, rec)

            def _export_pages(self):
                """``GET /admin/kvpages/<request_id>``: export a live
                session's committed KV-page bundle (integrity-hashed,
                ``disagg/kvtransfer.py``'s wire format) for disaggregated
                prefill hand-off. 404 for unknown/finished requests, for
                contiguous (non-paged) engines, and for schedulers
                without the export surface — the router then degrades to
                ticket-only migration, which re-prefills on the decode
                replica instead of adopting pages."""
                try:
                    rid = int(self.path.rsplit("/", 1)[1])
                except ValueError:
                    self._json(400, {"error": "bad session id"})
                    return
                export = getattr(
                    api.scheduler, "export_session_pages", None
                )
                try:
                    bundle = export(rid) if callable(export) else None
                except Exception as e:  # noqa: BLE001 — admin plane
                    # answers JSON (e.g. a device-op timeout on a wedged
                    # step); the router degrades to ticket-only migration
                    self._json(503, {
                        "error": f"kv page export failed: {e}",
                        "reason": "export_failed",
                        "request_id": rid,
                    })
                    return
                if bundle is None:
                    self._json(404, {
                        "error": "no exportable kv pages "
                                 "(unknown/finished session, or this "
                                 "replica runs a contiguous kv cache)",
                        "request_id": rid,
                    })
                    return
                self._json(200, bundle)

            def _admin_kvimport(self, body: dict):
                """``POST /admin/kvimport``: verify + adopt a KV-page
                bundle exported from another replica's
                ``/admin/kvpages/<id>``. Every page hash re-verifies
                BEFORE any pool mutation; adoption is refcount-correct
                (``KVPagePool.adopt``) and pins the chain like a parked
                session, so a following ``/admin/migrate`` of the same
                session finds the prefix in the tree and prefills
                tail-only. A pool-exhausted adoption answers the same
                typed 429 + Retry-After shape every admission shed uses
                (the router's fallback is the monolithic path — the
                session is still live on the prefill replica)."""
                from ..disagg.kvtransfer import KVTransferError, adopt_bundle
                from ..runtime.kvpool import PoolExhausted

                engine = getattr(api.scheduler, "engine", None)
                pool = getattr(engine, "kvpool", None)
                if pool is None:
                    self._json(409, {
                        "error": "kv import needs a paged engine "
                                 "(--paged-kv) on this replica",
                    })
                    return
                # through the scheduler loop's step boundary: the adopt
                # mutates the pool and writes device pages, which must
                # not race the pipelined chain's cache donation
                run = getattr(api.scheduler, "run_device_op", None)
                try:
                    if callable(run):
                        receipt = run(lambda: adopt_bundle(pool, engine, body))
                    else:
                        # dlint: ok[device-affinity] scheduler stand-ins without run_device_op have no loop thread racing the adopt
                        receipt = adopt_bundle(pool, engine, body)
                except KVTransferError as e:
                    # 422: the bundle itself is bad (corrupt, wrong
                    # geometry) — NOT retryable against this payload
                    self._json(422, {"error": str(e), "reason": e.reason})
                    return
                except PoolExhausted as e:
                    self._reject(AdmissionRejected(
                        "pool_exhausted", retry_after_s=2.0,
                    ))
                    del e
                    return
                except Exception as e:  # noqa: BLE001 — admin plane
                    # answers JSON, never a raw handler stack trace
                    self._json(500, {"error": str(e)})
                    return
                receipt["replica"] = api.replica_id
                self._json(200, receipt)

            def _admin_migrate(self, body: dict):
                """``POST /admin/migrate``: accept a session exported
                from another replica (the admit wire record
                ``/admin/session/<id>`` serves) and regenerate it here
                byte-identically through NORMAL breaker-gated admission —
                PR 10's deterministic replay as a migration primitive.
                The client (usually the router) then reattaches via
                ``GET /v1/stream/<id>`` + ``Last-Event-ID``; the relay
                re-buffers the whole regenerated stream from base=0 and
                Last-Event-ID alone picks the resume point (zero lost,
                zero duplicated tokens). A shed (breaker open, queue
                full, draining, pool exhausted) answers with the same
                typed 429/503 + Retry-After shape every admission shed
                uses, so routers retry elsewhere on the hint."""
                try:
                    entry = entry_from_admit_record(body)
                except ValueError as e:
                    self._json(400, {"error": f"bad migration record: {e}"})
                    return
                if entry.stream and api.resume is None:
                    # without a resume registry the regenerated stream
                    # has nowhere to buffer and no reattach route — a
                    # clear config error, not a retryable shed
                    self._json(409, {
                        "error": "stream migration needs "
                                 "--reconnect-grace > 0 on the target "
                                 "replica (no resume registry)",
                    })
                    return
                # id-collision remap: every replica numbers requests
                # from 1, so the injected ORIGINAL id routinely names a
                # LIVE request here — registering under it would clobber
                # that request's relay/session record and hand its
                # reattaching client ANOTHER user's stream. A live
                # session record (admitted) or registry entry (streamed,
                # queued ones register at build time) means collision:
                # re-admit under a fresh local id. The response's
                # request_id is authoritative either way — the router
                # reattaches by it, never by the ticket's original id.
                export = getattr(api.scheduler, "export_session", None)
                live = (
                    callable(export)
                    and export(entry.request_id) is not None
                ) or (
                    api.resume is not None
                    and api.resume.contains(entry.request_id)
                )
                if live:
                    entry.request_id = fresh_request_id()
                req, registered = attach_recovered_stream(
                    api.scheduler, entry, api.resume
                )
                try:
                    api.scheduler.submit(req)
                except AdmissionRejected as e:
                    if registered:
                        # nothing will ever resolve the future — drop
                        # the entry or the registry leaks one per shed
                        api.resume.discard(req.id)
                    self._reject(e, key=req.id)
                    return
                except Exception as e:  # noqa: BLE001 — a migrate inject
                    # must answer JSON, never a raw handler stack trace
                    if registered:
                        api.resume.discard(req.id)
                    self._json(500, {"error": str(e), "request_id": req.id})
                    return
                self._json(200, {
                    "request_id": req.id,
                    "stream_path": f"/v1/stream/{req.id}",
                    "watermark": entry.watermark,
                    "replica": api.replica_id,
                })

            def _resume_stream(self):
                """GET /v1/stream/<request_id> + ``Last-Event-ID``: the
                reconnect half of resumable SSE. 404s when resumption is
                off (--reconnect-grace 0, the default), the id is
                unknown, or the grace window expired."""
                if api.resume is None:
                    self._json(404, {
                        "error": "stream resumption disabled "
                                 "(--reconnect-grace is 0)",
                    })
                    return
                try:
                    rid = int(self.path.rsplit("/", 1)[1])
                except ValueError:
                    self._json(400, {"error": "bad stream id"})
                    return
                raw = self.headers.get("Last-Event-ID")
                try:
                    # no Last-Event-ID -> resume from the relay's base
                    # (0 for recovered streams: without the client's own
                    # position there is no safe skip point — the full
                    # regenerated stream replays)
                    after = None if raw is None else int(raw)
                except ValueError:
                    self._json(400, {"error": f"bad Last-Event-ID {raw!r}"})
                    return
                entry = api.resume.attach(rid)
                if entry is None:
                    self._json(404, {
                        "error": "unknown or expired stream "
                                 "(reconnect-grace window passed?)",
                        "request_id": rid,
                    })
                    return
                req, relay, kind, gen = entry
                chunk_fn = (
                    api_types.completion_chunk_response
                    if kind == "completion"
                    else api_types.chat_chunk_response
                )
                self._sse_headers(request_id=req.id)
                try:
                    api._pump(req, relay, gen,
                              relay.base if after is None else after,
                              self._sse_chunk, chunk_fn)
                    self.wfile.write(b"data: [DONE]\n\n")
                except (BrokenPipeError, ConnectionError, OSError):
                    api.resume.detach(rid)  # gone again: restart the grace clock
                except Exception as e:  # headers already out: SSE error event
                    self._sse_chunk({"error": str(e), "request_id": rid})
                    self.wfile.write(b"data: [DONE]\n\n")

            def do_POST(self):
                routes = {
                    "/v1/chat/completions": (
                        api.build_request, api.handle_chat_completion
                    ),
                    "/v1/completions": (
                        api.build_completion_request, api.handle_completion
                    ),
                }
                route = routes.get(self.path)
                admin = self.path in ("/admin/migrate", "/admin/kvimport")
                if route is None and not admin:
                    self._json(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                if self.path == "/admin/migrate":
                    # fleet migration inject (see _admin_migrate): rides
                    # the same body parse, then the recovery path
                    self._admin_migrate(body)
                    return
                if self.path == "/admin/kvimport":
                    # disagg page adoption (see _admin_kvimport)
                    self._admin_kvimport(body)
                    return
                build_fn, handle_fn = route
                # fleet trace context: accept a VALID X-DLlama-Trace wire
                # value (the router mints one per request; clients may
                # send their own); malformed/absent values are dropped —
                # tracing never fails or sheds a request
                ctx = TraceContext.parse(self.headers.get(TRACE_HEADER))
                trace = ctx.to_header() if ctx is not None else None
                # request id in EVERY failure payload once a Request exists
                # (satellite: a streamed failure must correlate with the
                # server's per-request log lines); None before build_fn
                # succeeds — those are input errors with no request yet
                req = None

                def err(payload: dict) -> dict:
                    if req is not None:
                        payload["request_id"] = req.id
                    return payload

                try:
                    if body.get("stream"):
                        # validate AND submit BEFORE committing SSE headers so
                        # bad input still gets a proper 400 and a shed request
                        # (queue full / draining) a proper 429/503
                        prepared = build_fn(body, streaming=True, trace=trace)
                        req = prepared[0]
                        try:
                            api.scheduler.submit(req)
                        except BaseException:
                            # shed (breaker/queue/draining): the relay
                            # was registered at build time, and nothing
                            # will ever resolve this future or detach it
                            # — drop the entry or the registry leaks one
                            # per shed streaming POST
                            if api.resume is not None:
                                api.resume.discard(req.id)
                            raise
                        try:
                            self._sse_headers(request_id=req.id)
                        except BaseException:
                            # client vanished between submit and the header
                            # commit: no pump will ever run, so cancel or the
                            # lane generates max_tokens into an orphaned queue
                            req.cancel()
                            raise
                        try:
                            handle_fn(body, send_chunk=self._sse_chunk,
                                      prepared=prepared)
                            self.wfile.write(b"data: [DONE]\n\n")
                        except (BrokenPipeError, ConnectionError, OSError):
                            # client gone; _run_request already cancelled
                            # the request (or parked it in the resume
                            # registry's grace window)
                            return
                        except Exception as e:  # headers already sent: SSE error event
                            self._sse_chunk(err({"error": str(e)}))
                            self.wfile.write(b"data: [DONE]\n\n")
                    else:
                        prepared = build_fn(body, streaming=False, trace=trace)
                        req = prepared[0]
                        self._json(200, handle_fn(body, prepared=prepared))
                except AdmissionRejected as e:  # shed before any headers
                    self._reject(e, key=req.id if req is not None else None)
                except SchedulerStalled as e:
                    # wedged scheduler: retryable 503 naming the request
                    # (streamed variants surface as terminal SSE error
                    # chunks through the generic handler above — their
                    # headers are already out). Jittered like every shed.
                    retry = jittered_retry_after(
                        30.0, req.id if req is not None else next(_shed_keys)
                    )
                    self._json(
                        503, err({"error": str(e), "reason": "stalled"}),
                        headers={"Retry-After": str(max(1, round(retry)))},
                    )
                except ValueError as e:
                    self._json(400, err({"error": str(e)}))
                except Exception as e:  # generation failure
                    self._json(500, err({"error": str(e)}))

        httpd = ThreadingHTTPServer((host, port), Handler)
        if self.replica_id is None:
            # default fleet identity: where this replica listens (read
            # off the bound socket, so port=0 ephemeral binds resolve).
            # A wildcard bind substitutes the machine's hostname — every
            # replica defaulting to "0.0.0.0:8080" would make the
            # attribution header identical (useless) across the fleet.
            id_host = host
            if id_host in ("", "0.0.0.0", "::"):
                import socket as _socket

                id_host = _socket.gethostname()
            self.replica_id = f"{id_host}:{httpd.server_address[1]}"
        # fleet span attribution: once the replica's identity is known,
        # every span the hub emits carries it as a `replica` arg — the
        # merged fleet timeline needs each event to name its source even
        # after docs from several replicas are interleaved
        tel = self._telemetry()
        if getattr(tel, "replica", None) is None:
            tel.replica = self.replica_id
        self._httpd = httpd
        return httpd

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
