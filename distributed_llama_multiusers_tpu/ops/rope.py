"""Rotary position embeddings, interleaved-pair convention.

The `.m` format stores Q/K weights pre-permuted to the interleaved-rotary
layout (converter/convert-hf.py:11-14), and the reference rotates adjacent
pairs (x[2i], x[2i+1]) per head using a precomputed cos/sin cache
(src/nn/nn-cpu-ops.cpp:1091-1120, cache built in src/nn/nn-core.cpp:323-340).
This module reproduces that exactly, including Llama-3.1 frequency scaling
(src/nn/nn-core.cpp:307-321).

The cache covers the full head dim (TP slicing is expressed through sharding
annotations instead of the reference's per-node qShift windows).
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp


def _scale_frequency_llama3(
    freq: float,
    scaling_factor: float,
    low_freq_factor: float,
    high_freq_factor: float,
    orig_max_seq_len: int,
) -> float:
    # src/nn/nn-core.cpp:307-321
    wave_len = 2.0 * math.pi / freq
    high_freq_wavelen = orig_max_seq_len / high_freq_factor
    if wave_len < high_freq_wavelen:
        return freq
    low_freq_wavelen = orig_max_seq_len / low_freq_factor
    if wave_len > low_freq_wavelen:
        return freq / scaling_factor
    smooth = (orig_max_seq_len / wave_len - low_freq_factor) / (high_freq_factor - low_freq_factor)
    return (1 - smooth) * freq / scaling_factor + smooth * freq


def build_rope_cache(
    seq_len: int,
    head_size: int,
    rope_theta: float = 10000.0,
    scaling_factor: float = 1.0,
    low_freq_factor: float = 0.0,
    high_freq_factor: float = 0.0,
    orig_max_seq_len: int = 0,
    dtype=np.float32,
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (cos, sin), each [seq_len, head_size // 2], float32.

    Frequencies follow the reference: pair p (elements 2p, 2p+1 of a head)
    uses theta^(-2p/head_size) (src/nn/nn-core.cpp:328-333).
    """
    half = head_size // 2
    freqs = np.empty(half, dtype=np.float64)
    apply_scaling = scaling_factor != 1.0
    for p in range(half):
        freq = 1.0 / (rope_theta ** ((2 * p) / head_size))
        if apply_scaling:
            freq = _scale_frequency_llama3(
                freq, scaling_factor, low_freq_factor, high_freq_factor, orig_max_seq_len
            )
        freqs[p] = freq
    t = np.arange(seq_len, dtype=np.float64)[:, None] * freqs[None, :]
    return np.cos(t).astype(dtype), np.sin(t).astype(dtype)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
    """Rotate interleaved pairs.

    x: [B, T, n_heads, head_size]; cos/sin: [seq_len, head_size//2];
    positions: [B, T] int32. Returns same shape/dtype as x.
    """
    b, t, h, d = x.shape
    xf = x.astype(jnp.float32).reshape(b, t, h, d // 2, 2)
    x0 = xf[..., 0]
    x1 = xf[..., 1]
    c = cos[positions][:, :, None, :]  # [B, T, 1, d/2]
    s = sin[positions][:, :, None, :]
    r0 = x0 * c - x1 * s
    r1 = x0 * s + x1 * c
    out = jnp.stack([r0, r1], axis=-1).reshape(b, t, h, d)
    return out.astype(x.dtype)
