"""Chrome trace-event export: the span ring as a Perfetto-loadable JSON.

Output is the Trace Event Format's JSON-object form
(``{"traceEvents": [...], "displayTimeUnit": "ms"}``) using only the
parts every viewer (chrome://tracing, ui.perfetto.dev) honours:

- one process (pid 1, named for the model/server),
- one *thread* per logical track — ``lane0..laneN`` (requests pinned to
  their KV lane), ``pipeline`` (per-dispatch step slices), ``queue``
  (submit→admit waits) — named via ``M``/``thread_name`` metadata and
  ordered via ``thread_sort_index``,
- ``X`` complete events (``ts``+``dur`` in µs) for spans,
- ``i`` thread-scoped instants for admissions, finishes, flushes.

Fused prefill+decode dispatches render as ``step.fused`` slices on the
``pipeline`` track (plus a ``prefill.fused`` slice on the admitting
lane's track), so "did the admission actually ride the chain" is a thing
you *see*, not infer from counters.
"""

from __future__ import annotations

import json
from typing import Iterable

from .spans import SpanEvent, SpanTracer

PROCESS_NAME = "dllama-serving"


def _track_order(track: str) -> tuple:
    """Stable display order: lanes first (numeric), then pipeline, queue,
    then anything else alphabetically."""
    if track.startswith("lane"):
        suffix = track[4:]
        if suffix.isdigit():
            return (0, int(suffix), track)
    return ({"pipeline": 1, "queue": 2}.get(track, 3), 0, track)


def chrome_trace(events: Iterable[SpanEvent], origin: float = 0.0) -> dict:
    """Render span events into a Chrome trace-event JSON object.

    ``origin`` (the tracer's perf_counter epoch) rebases timestamps so
    the trace starts near t=0; event ``ts``/``dur`` come out in µs as the
    format requires."""
    events = list(events)
    tracks = sorted({e.track for e in events}, key=_track_order)
    tids = {t: i + 1 for i, t in enumerate(tracks)}
    # metadata events carry ts 0: the format ignores it, and a uniform
    # required-field set (name/ph/pid/tid/ts) keeps consumers simple
    out: list[dict] = [{
        "name": "process_name", "ph": "M", "pid": 1, "tid": 0, "ts": 0,
        "args": {"name": PROCESS_NAME},
    }]
    for track, tid in tids.items():
        out.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid, "ts": 0,
            "args": {"name": track},
        })
        out.append({
            "name": "thread_sort_index", "ph": "M", "pid": 1, "tid": tid,
            "ts": 0, "args": {"sort_index": tid},
        })
    for e in events:
        args = dict(e.args) if e.args else {}
        if e.req_id is not None:
            args.setdefault("request_id", e.req_id)
        if e.seq:
            # the poller cursor rides each event too, so a consumer can
            # resume from any event it already holds, not just the
            # response-level "cursor" field
            args.setdefault("seq", e.seq)
        rec = {
            "name": e.name,
            "ph": e.ph,
            "pid": 1,
            "tid": tids[e.track],
            "ts": round((e.ts - origin) * 1e6, 3),
            "args": args,
        }
        if e.ph == "X":
            rec["dur"] = round(e.dur * 1e6, 3)
        elif e.ph == "i":
            rec["s"] = "t"  # thread-scoped instant
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def tracer_chrome_trace(tracer: SpanTracer, since: int = 0,
                        trace_id: str | None = None) -> dict:
    """Render the tracer's window; ``since``/``trace_id`` filter the ring
    (satellite: incremental polling + per-trace extraction). The returned
    doc carries a top-level ``cursor`` — pass it back as ``since=`` to get
    only newer events; viewers ignore unknown top-level keys."""
    events = tracer.snapshot(since=since, trace_id=trace_id)
    doc = chrome_trace(events, origin=tracer.origin)
    doc["cursor"] = events[-1].seq if events else since
    return doc


FLEET_PROCESS_NAME = "dllama-fleet"


def merge_chrome_traces(parts: list) -> dict:
    """Merge per-process Chrome-trace docs into ONE fleet timeline.

    ``parts`` is ``[(source, doc, offset_us, uncertainty_us), ...]`` —
    ``source`` names the process (``router``, replica ids), ``doc`` is
    that process's ``chrome_trace`` output, and ``offset_us`` is the
    estimated clock offset to ADD to its timestamps to land them on the
    merge caller's timebase (each process's ``perf_counter`` has its own
    arbitrary origin). The correction is applied so the timeline lines
    up, and it is NOT silent: every migrated event's args carry
    ``clock_offset_us`` + ``clock_uncertainty_us`` (the RTT/2 error bound
    of the /load-scrape estimate), so a viewer can tell measured
    ordering from estimated alignment.

    Tracks come out as ``<source>/<track>`` rows — router queue next to
    the prefill replica's lane next to the decode replica's lane, the
    adjacency the ISSUE's merged-timeline acceptance reads."""
    out: list[dict] = [{
        "name": "process_name", "ph": "M", "pid": 1, "tid": 0, "ts": 0,
        "args": {"name": FLEET_PROCESS_NAME},
    }]
    merged: list[dict] = []
    tid_next = 1
    for source, doc, offset_us, uncertainty_us in parts:
        events = (doc or {}).get("traceEvents", [])
        track_names = {
            e.get("tid"): (e.get("args") or {}).get("name", "")
            for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        }
        remap: dict = {}
        for e in events:
            if e.get("ph") == "M":
                continue
            old_tid = e.get("tid", 0)
            if old_tid not in remap:
                track = track_names.get(old_tid) or f"t{old_tid}"
                remap[old_tid] = tid_next
                out.append({
                    "name": "thread_name", "ph": "M", "pid": 1,
                    "tid": tid_next, "ts": 0,
                    "args": {"name": f"{source}/{track}"},
                })
                out.append({
                    "name": "thread_sort_index", "ph": "M", "pid": 1,
                    "tid": tid_next, "ts": 0,
                    "args": {"sort_index": tid_next},
                })
                tid_next += 1
            ne = dict(e)
            ne["pid"] = 1
            ne["tid"] = remap[old_tid]
            ne["ts"] = round(float(e.get("ts", 0.0)) + offset_us, 3)
            args = dict(e.get("args") or {})
            args["span_source"] = source
            args["clock_offset_us"] = round(float(offset_us), 1)
            args["clock_uncertainty_us"] = round(float(uncertainty_us), 1)
            ne["args"] = args
            merged.append(ne)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": out + merged, "displayTimeUnit": "ms"}


def dump_chrome_trace(tracer: SpanTracer, path: str) -> dict:
    """Write the tracer's current window to ``path`` and return the
    rendered document (the bench reports slice counts from it)."""
    doc = tracer_chrome_trace(tracer)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return doc
