"""dlint v5: the resource-lifecycle surface model and its two checks
(resource-balance, device-affinity) — fixture-tested as programs, plus
rot-guards binding the model to the REAL declarations in the tree.

Layers, per the test_dlint.py contract:

- **known-bad / known-good fixtures** per excuse and legality rule, so
  each rule is regression-tested rather than trusted on the current
  tree's verdict;
- **real-declaration rot-guards** — the shipped ``_dlint_acquires`` /
  ``_dlint_releases`` / ``_dlint_device_affine`` / ``_dlint_loop_roots``
  declarations must keep reaching the model (a renamed method would
  otherwise silently hollow the checks out);
- **reporting plumbing** — finalize findings survive ``--changed``
  scoping, and the new rule ids reach the SARIF/list surfaces.

Pure-stdlib imports: these tests run without jax.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from distributed_llama_multiusers_tpu.analysis import (
    Analyzer,
    default_checkers,
)
from distributed_llama_multiusers_tpu.analysis.cli import main as dlint_main
from distributed_llama_multiusers_tpu.analysis.resourcemodel import (
    build_model,
    resource_dot,
)

PACKAGE = Path(__file__).resolve().parent.parent / (
    "distributed_llama_multiusers_tpu"
)

# the real files carrying lifecycle declarations (rot-guard scope)
DECL_FILES = [
    PACKAGE / "runtime" / "kvpool.py",
    PACKAGE / "runtime" / "engine.py",
    PACKAGE / "runtime" / "scheduler.py",
    PACKAGE / "serving" / "resume.py",
    PACKAGE / "serving" / "journal.py",
]


def run_on(tmp_path: Path, files: dict[str, str], check_only=None):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    analyzer = Analyzer(default_checkers())
    return analyzer.run(
        [tmp_path], baseline=set(), root=tmp_path, check_only=check_only
    )


def of(findings, check):
    return [f for f in findings if f.check == check]


# a minimal declared kind shared by the resource-balance fixtures
POOL = """
    class Pool:
        _dlint_acquires = {"widget": ("grab",)}
        _dlint_releases = {"widget": ("put_back",)}

        def grab(self):
            return object()

        def put_back(self, h):
            pass
"""


# -- resource-balance: known bad ---------------------------------------------


def test_raise_after_acquire_fires(tmp_path):
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        def leaky(pool, n):
            h = pool.grab()
            if n > 3:
                raise ValueError("shed")
            return h
    """}), "resource-balance")
    assert len(findings) == 1
    f = findings[0]
    assert "leaky" in f.message and "widget" in f.message
    assert "grab()" in f.message
    assert f.path.endswith("use.py")


def test_raise_in_later_except_arm_fires(tmp_path):
    """A raise inside the handler of a try AFTER the acquire is not the
    acquire-may-have-failed shape — the widget is held."""
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        def leaky(pool):
            h = pool.grab()
            try:
                step()
            except RuntimeError:
                raise ValueError("held!")
            return h

        def step():
            pass
    """}), "resource-balance")
    assert len(findings) == 1


def test_half_declared_kind_fires(tmp_path):
    findings = of(run_on(tmp_path, {"pool.py": """
        class Pool:
            _dlint_acquires = {"widget": ("grab",)}

            def grab(self):
                return object()
    """}), "resource-balance")
    assert any("no release" in f.message for f in findings)


def test_declared_method_must_exist(tmp_path):
    """Rot-guard: declaring a method the class no longer defines is a
    finding (the declaration would silently stop covering anything)."""
    findings = of(run_on(tmp_path, {"pool.py": """
        class Pool:
            _dlint_acquires = {"widget": ("grab_renamed",)}
            _dlint_releases = {"widget": ("put_back",)}

            def grab(self):
                return object()

            def put_back(self, h):
                pass
    """}), "resource-balance")
    assert any("grab_renamed" in f.message for f in findings)


# -- resource-balance: the excuse rules (known good) -------------------------


def test_raise_in_acquires_own_except_arm_ok(tmp_path):
    """Excuse 1: the acquire itself may be what failed — nothing held."""
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        def careful(pool):
            try:
                h = pool.grab()
            except MemoryError:
                raise ValueError("pool exhausted")
            return h
    """}), "resource-balance")
    assert findings == []


def test_release_between_acquire_and_raise_ok(tmp_path):
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        def careful(pool, n):
            h = pool.grab()
            if n > 3:
                pool.put_back(h)
                raise ValueError("shed")
            return h
    """}), "resource-balance")
    assert findings == []


def test_releasing_handler_catches_raise_ok(tmp_path):
    """Excuse 3: cleanup-at-catch — an enclosing try's handler releases,
    through a transitive wrapper."""
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        def _cleanup(pool, h):
            pool.put_back(h)

        def careful(pool, n):
            h = pool.grab()
            try:
                if n > 3:
                    raise ValueError("shed")
            except ValueError:
                _cleanup(pool, h)
                raise
            return h
    """}), "resource-balance")
    assert findings == []


def test_every_call_site_releasing_ok(tmp_path):
    """Excuse 4 (interprocedural): the owner one frame up releases on
    failure at EVERY call site."""
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        def claim(pool, n):
            h = pool.grab()
            if n > 3:
                raise ValueError("shed mid-claim")
            return h

        def owner(pool, n):
            try:
                return claim(pool, n)
            except ValueError:
                pool.put_back(None)
                raise
    """}), "resource-balance")
    assert findings == []


def test_unprotected_call_site_still_fires(tmp_path):
    """Excuse 4's ALL-sites rule: one bare call site keeps the finding."""
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        def claim(pool, n):
            h = pool.grab()
            if n > 3:
                raise ValueError("shed mid-claim")
            return h

        def owner(pool, n):
            try:
                return claim(pool, n)
            except ValueError:
                pool.put_back(None)
                raise

        def bare(pool):
            return claim(pool, 9)
    """}), "resource-balance")
    assert len(findings) == 1


def test_waived_transfer_ok(tmp_path):
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        class Parked(Exception):
            pass

        def park(pool):
            h = pool.grab()
            # dlint: ok[resource-balance] ticket transfer to the parker
            raise Parked(h)
    """}), "resource-balance")
    assert findings == []


def test_return_is_ownership_transfer(tmp_path):
    """A plain return is never flagged — returning the acquired resource
    IS the normal API shape."""
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        def handout(pool):
            return pool.grab()
    """}), "resource-balance")
    assert findings == []


def test_vocabulary_functions_exempt(tmp_path):
    """Proxy/mock implementations NAMED like the vocabulary (a facade's
    own grab()) are implementations, not consumers."""
    findings = of(run_on(tmp_path, {"pool.py": POOL, "use.py": """
        class Facade:
            def grab(self):
                h = self.pool.grab()
                if h is None:
                    raise MemoryError("exhausted")
                return h
    """}), "resource-balance")
    assert findings == []


# -- resource-balance: the host-page (swap tier) vocabulary -------------------

TIER = """
    class HostTier:
        _dlint_releases = {"host-page": ("put", "discard")}

        def put(self, key, blk, payload):
            return True

        def discard(self, key):
            pass

    class Pool:
        _dlint_acquires = {"host-page": ("take_pending_swapouts",)}

        def take_pending_swapouts(self):
            return []
"""


def test_host_page_drain_leak_fires(tmp_path):
    """Known-bad: a drain that takes the staged swap-outs then raises
    before the tier stores them strands the pages — neither swapped nor
    rebuildable (the deposit already left the pool)."""
    findings = of(run_on(tmp_path, {"tier.py": TIER, "use.py": """
        def drain(pool, tier, ok):
            staged = pool.take_pending_swapouts()
            if not ok:
                raise RuntimeError("device read failed")
            for page in staged:
                tier.put(*page)
    """}), "resource-balance")
    assert len(findings) == 1
    assert "host-page" in findings[0].message
    assert "take_pending_swapouts()" in findings[0].message


def test_host_page_drain_discard_on_failure_ok(tmp_path):
    """Known-good: discarding the staged batch before the raise settles
    the ownership (excuse 2 — release between acquire and raise)."""
    findings = of(run_on(tmp_path, {"tier.py": TIER, "use.py": """
        def drain(pool, tier, ok):
            staged = pool.take_pending_swapouts()
            if not ok:
                tier.discard(staged)
                raise RuntimeError("device read failed")
            for page in staged:
                tier.put(*page)
    """}), "resource-balance")
    assert findings == []


# -- device-affinity ----------------------------------------------------------

ENGINE = """
    class Engine:
        _dlint_device_affine = ("touch_cache",)

        def touch_cache(self):
            pass

        def helper(self):
            self.touch_cache()  # legal: declaring file
"""

SCHED = """
    class Sched:
        _dlint_loop_roots = ("_run",)

        def __init__(self, engine):
            self.engine = engine

        def _run(self):
            self._step()

        def _step(self):
            self.engine.touch_cache()  # legal: loop closure

        def run_device_op(self, fn):
            return fn()
"""


def test_off_loop_device_touch_fires(tmp_path):
    findings = of(run_on(tmp_path, {
        "engine.py": ENGINE, "sched.py": SCHED, "admin.py": """
        def admin_touch(engine):
            engine.touch_cache()
    """}), "device-affinity")
    assert len(findings) == 1
    f = findings[0]
    assert "touch_cache" in f.message and "admin_touch" in f.message
    assert f.path.endswith("admin.py")


def test_loop_closure_and_decl_file_ok(tmp_path):
    findings = of(run_on(tmp_path, {
        "engine.py": ENGINE, "sched.py": SCHED,
    }), "device-affinity")
    assert findings == []


def test_run_device_op_lambda_ok(tmp_path):
    findings = of(run_on(tmp_path, {
        "engine.py": ENGINE, "sched.py": SCHED, "admin.py": """
        def admin_ok(sched, engine):
            return sched.run_device_op(lambda: engine.touch_cache())
    """}), "device-affinity")
    assert findings == []


def test_funnel_alias_ok(tmp_path):
    """A local alias of run_device_op (including the getattr probe the
    HTTP layer uses) still counts as the funnel."""
    findings = of(run_on(tmp_path, {
        "engine.py": ENGINE, "sched.py": SCHED, "admin.py": """
        def admin_ok(sched, engine):
            run = getattr(sched, "run_device_op", None)
            if run is None:
                return None
            return run(lambda: engine.touch_cache())
    """}), "device-affinity")
    assert findings == []


def test_facade_class_ok(tmp_path):
    """A class defining a declared device-affine name is part of the
    engine surface (RootControlEngine) — its method bodies inherit the
    affinity contract even when calling a DIFFERENT primitive."""
    findings = of(run_on(tmp_path, {
        "engine.py": ENGINE, "sched.py": SCHED, "proxy.py": """
        class Proxy:
            def __init__(self, inner):
                self.inner = inner

            def touch_cache(self):
                self.inner.touch_cache()

            def reset(self):
                self.inner.touch_cache()
    """}), "device-affinity")
    assert findings == []


def test_caller_legality_fixpoint_ok(tmp_path):
    """A helper whose EVERY call site is legal (a funnel lambda)
    inherits legality — the disagg export/import helper shape."""
    findings = of(run_on(tmp_path, {
        "engine.py": ENGINE, "sched.py": SCHED, "helpers.py": """
        def export_pages(engine):
            engine.touch_cache()
            return []

        def endpoint(sched, engine):
            return sched.run_device_op(lambda: export_pages(engine))
    """}), "device-affinity")
    assert findings == []


def test_device_affinity_waiver_ok(tmp_path):
    findings = of(run_on(tmp_path, {
        "engine.py": ENGINE, "sched.py": SCHED, "worker.py": """
        def replay_loop(engine):
            # dlint: ok[device-affinity] worker replay loop IS the batching thread
            engine.touch_cache()
    """}), "device-affinity")
    assert findings == []


def test_loop_root_must_exist(tmp_path):
    findings = of(run_on(tmp_path, {"sched.py": """
        class Sched:
            _dlint_loop_roots = ("_gone",)

            def _run(self):
                pass
    """}), "device-affinity")
    assert any("_gone" in f.message for f in findings)


# -- rot-guards against the real tree ----------------------------------------


def test_real_declarations_reach_the_model():
    model = build_model(DECL_FILES)
    assert set(model.kinds) == {
        "kv-page", "host-page", "session-record", "stream-entry",
        "journal-mark",
    }
    kv = model.kinds["kv-page"]
    assert {"admit", "adopt", "paged_admit"} == set(kv.acquires)
    assert "paged_finish" in kv.releases and "finish" in kv.releases
    # the swap tier's staged-page kind: a drained deposit is owned until
    # the tier stores (put) or refuses (discard) it
    hp = model.kinds["host-page"]
    assert set(hp.acquires) == {"take_pending_swapouts"}
    assert set(hp.releases) == {"put", "discard"}
    assert set(model.kinds["session-record"].acquires) == {"_mirror_admit"}
    assert set(model.kinds["stream-entry"].acquires) == {"register"}
    assert set(model.kinds["journal-mark"].releases) == {"record_finish"}
    assert set(model.device_methods) == {
        "apply_paged_admit", "copy_lane", "paged_unmap_all",
        "export_kv_page", "import_kv_page",
        "swap_out_pages", "swap_in_pages",
    }
    assert model.loop_roots == {
        ("scheduler.py", "ContinuousBatchingScheduler"): ("_run",)
    }


def test_real_host_page_releasers_span_the_drain():
    """The engine's drain (the only consumer of staged swap-outs) must
    keep reaching the tier's release vocabulary — a renamed put/discard
    would silently hollow the host-page balance check out."""
    model = build_model(DECL_FILES + [PACKAGE / "utils" / "testing.py"])
    releasers = model.transitive_releasers("host-page")
    assert {"put", "discard", "drain_kv_swapouts"} <= releasers


def test_real_loop_closure_reaches_dispatch():
    """The _run -> _serve_loop -> ... closure must keep covering the
    loop-thread methods that legitimately touch donated pytrees."""
    model = build_model(DECL_FILES)
    closure = model.loop_closure("scheduler.py", "ContinuousBatchingScheduler")
    assert {"_run", "_serve_loop", "_start_request"} <= closure


def test_real_transitive_releasers_span_wrappers():
    """_fail_request reaches paged_finish through _paged_release — the
    chain the interprocedural excuse depends on."""
    model = build_model(DECL_FILES)
    releasers = model.transitive_releasers("kv-page")
    assert {"paged_finish", "_paged_release", "_fail_request"} <= releasers


def test_resource_dot_draws_kinds_and_waivers(tmp_path):
    model = build_model(DECL_FILES)
    dot = resource_dot(model)
    assert dot.startswith("digraph resources")
    assert '"[kv-page]"' in dot and '"paged_admit" -> "[kv-page]"' in dot
    # a waived transfer renders dashed, attributed to its owner function
    (tmp_path / "pool.py").write_text(textwrap.dedent(POOL))
    (tmp_path / "use.py").write_text(textwrap.dedent("""
        def park(pool):
            # dlint: ok[resource-balance] ticket transfer
            raise RuntimeError(pool.grab())
    """))
    dot2 = resource_dot(build_model([tmp_path]))
    assert 'style=dashed' in dot2 and '"park"' in dot2


# -- reporting plumbing -------------------------------------------------------


def test_finalize_findings_survive_changed_scope(tmp_path):
    """--changed keeps cross-file findings: the leak is reported even
    when the leaky file is NOT in the changed set."""
    files = {"pool.py": POOL, "use.py": """
        def leaky(pool, n):
            h = pool.grab()
            if n > 3:
                raise ValueError("shed")
            return h
    """}
    for rel, src in files.items():
        (tmp_path / rel).write_text(textwrap.dedent(src), encoding="utf-8")
    analyzer = Analyzer(default_checkers())
    findings = analyzer.run(
        [tmp_path], baseline=set(), root=tmp_path,
        check_only={(tmp_path / "pool.py").resolve()},
    )
    assert len(of(findings, "resource-balance")) == 1


def test_new_checks_listed_and_in_sarif(tmp_path, capsys):
    assert dlint_main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    assert "resource-balance" in out and "device-affinity" in out
    for rel, src in {"pool.py": POOL, "use.py": """
        def leaky(pool, n):
            h = pool.grab()
            if n > 3:
                raise ValueError("shed")
            return h
    """}.items():
        (tmp_path / rel).write_text(textwrap.dedent(src), encoding="utf-8")
    rc = dlint_main([str(tmp_path), "--no-baseline", "--format", "sarif"])
    out = capsys.readouterr().out
    assert rc == 1
    assert '"resource-balance"' in out  # ruleId + rule metadata


def test_cli_resource_table_and_graph(capsys):
    assert dlint_main(["--resource-table"]) == 0
    out = capsys.readouterr().out
    assert "kv-page" in out and "device-affine" in out
    assert "loop roots ContinuousBatchingScheduler" in out
    assert dlint_main(["--graph", "resources"]) == 0
    assert capsys.readouterr().out.startswith("digraph resources")
