"""lock-blocking: no blocking construct while holding a known lock.

The package's locks guard short critical sections — counter bumps, queue
surgery, ring appends. Holding one across anything that can block turns
every other thread touching that lock into a convoy behind the slow
operation (and, for the pod control plane, into a distributed deadlock:
a broadcast under a lock serializes every process on one host's lock
hold). This check mechanizes two rules that previously lived in
comments:

- the PR 5 **wait-observer rule** — ``QosQueue.set_wait_observer``
  callbacks run OUTSIDE the queue lock (an observer/hook call under a
  known lock is a finding);
- the multihost **"never broadcast under a lock"** rule —
  ``broadcast_one_to_all`` / ``ControlPlane.send_*`` under any lock is a
  finding.

The blocking vocabulary (lockgraph.iter_blocking) extends the host-sync
pattern set: device->host transfers, socket/stream I/O (``sendall`` /
``recv`` / ``urlopen`` / ``print``), ``future.result()``, thread
``join``, ``time.sleep``, subprocess execution, collective/packet sends,
and observer/hook invocations. ``Condition.wait`` is judged in context:
waiting on the condition built over the lock you hold is the one
legitimate blocking-under-lock (that IS how condvars work — the wait
releases it); waiting on anything else while a lock is held parks the
thread with the lock still taken.

One level of intra-package calls is expanded: calling a function that
directly contains a blocking construct while holding a lock is flagged
at the call site. Sanctioned sites (the native build serialized behind
``native._lock``, the JSON logger's line write under ``_log_lock``)
carry ``# dlint: ok[lock-blocking] reason`` waivers naming why the hold
is the point.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, Project, SourceFile, nearest, walk_with_ancestors
from .lockgraph import LockModel, classify_blocking_call, module_stem


class LockBlockingChecker(Checker):
    name = "lock-blocking"
    description = (
        "blocking constructs (I/O, waits, sends, broadcasts, observer "
        "calls, subprocesses) while holding a declared lock convoy every "
        "other thread on that lock"
    )

    def check(self, sf: SourceFile, project: Project):
        model: LockModel = project.lock_model
        if model is None or not model.decls:
            return
        model.ensure_semantics()
        stem = module_stem(sf.path)
        for node, ancestors in walk_with_ancestors(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            cls = nearest(ancestors, ast.ClassDef)
            class_ctx = cls.name if cls is not None else None
            held = model.held_at(ancestors, class_ctx, stem)
            if not held:
                continue
            held_names = ", ".join(sorted({q for q, _ in held}))
            entry = classify_blocking_call(node)
            if entry is not None:
                kind, descr = entry
                if kind == "wait" and self._own_lock_wait(
                    node, held, model, class_ctx, stem
                ):
                    continue
                yield Finding(
                    self.name, sf.display, node.lineno,
                    f"{descr} while holding '{held_names}' blocks every "
                    "thread contending on that lock; move it outside the "
                    "critical section or waive with "
                    "'# dlint: ok[lock-blocking] <why the hold is the point>'",
                )
                continue
            # one level of intra-package calls: a callee that directly
            # blocks, invoked with the lock held, holds it just the same
            info = model._resolve_callee(node, sf, class_ctx)
            if info is not None and info.blocking:
                line, descr = info.blocking[0]
                yield Finding(
                    self.name, sf.display, node.lineno,
                    f"call to '{ast.unparse(node.func)}(...)' while holding "
                    f"'{held_names}' — the callee blocks ({descr} at "
                    f"line {line}); hoist the call out of the critical "
                    "section or waive with '# dlint: ok[lock-blocking] <why>'",
                )

    @staticmethod
    def _own_lock_wait(node: ast.Call, held, model: LockModel,
                       class_ctx, stem) -> bool:
        """``cv.wait()`` where cv aliases a held lock releases that lock
        for the duration — the legitimate condvar shape."""
        func = node.func
        if not isinstance(func, ast.Attribute):
            return False
        qual = model.resolve(func.value, class_ctx, stem)
        return qual is not None and qual in {q for q, _ in held}
