from .norm import rms_norm
from .rope import build_rope_cache, apply_rope
from .activations import silu, gelu
