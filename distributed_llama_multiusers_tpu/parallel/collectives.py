"""Quantization-compressed collectives.

The reference cuts TP sync bandwidth ~4x by shipping Q80 (int8 + fp16 block
scale) instead of f32 over its TCP mesh (ZQ pipe, src/llm.cpp:150,
src/nn/nn-network.cpp:537-569). On ICI bandwidth is rarely the bottleneck,
but the same trick applies on DCN-spanning meshes — so the framework offers
an int8-compressed all-gather built from shard_map primitives.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..quants.jax_codec import Q80_BLOCK, q80_decode_blocks, q80_encode_blocks


def q80_all_gather(x: jnp.ndarray, mesh: Mesh, axis: str = "tp") -> jnp.ndarray:
    """All-gather x's last dim across ``axis``, shipping int8+fp16 scales.

    x: sharded on its last axis over ``axis`` (each device holds its slice).
    Returns the full array, replicated over ``axis``; payload on the wire is
    ~25% of the f32 equivalent (34 bytes per 32 values, SURVEY.md §5.8).
    """
    n_axis_dims = x.ndim
    n_shards = mesh.shape[axis]
    if x.shape[-1] % (Q80_BLOCK * n_shards) != 0:
        raise ValueError(
            f"q80_all_gather needs last dim ({x.shape[-1]}) divisible by "
            f"{Q80_BLOCK} * mesh.shape[{axis!r}] ({n_shards}) so each device "
            f"slice is whole Q80 blocks"
        )

    def inner(local):
        # converter-mode rounding (ties-to-even vectorizes as one jnp.round)
        q, s = q80_encode_blocks(local, mode="converter")
        qg = jax.lax.all_gather(q, axis, axis=0)  # [n, ..., blk, 32]
        sg = jax.lax.all_gather(s, axis, axis=0)
        n = qg.shape[0]
        full = q80_decode_blocks(qg, sg, (n,) + local.shape)
        # concat device slices along the (last) sharded dim
        return jnp.concatenate([full[i] for i in range(n)], axis=-1)

    in_spec = P(*([None] * (n_axis_dims - 1) + [axis]))
    out_spec = P(*([None] * n_axis_dims))
    return shard_map(
        inner, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )(x)
