"""Tensor/header writers for the `.m` format (reference: converter/writer.py).

The quantizers are the framework's vectorized numpy codecs (bit-exact with
the reference's blockwise Q40/Q80 math) instead of per-block struct.pack
loops — the output bytes are identical, the writing is orders of magnitude
faster.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_llama_multiusers_tpu.formats.model_file import ModelHeader, write_model_header
from distributed_llama_multiusers_tpu.quants.codec import (
    FloatType,
    float_type_name,
    quantize_q40,
    quantize_q80,
)

FLOAT_TYPES = {"f32": FloatType.F32, "f16": FloatType.F16, "q40": FloatType.Q40, "q80": FloatType.Q80}


def parse_float_type(name: str) -> int:
    if name not in FLOAT_TYPES:
        raise ValueError(f"{name} is not supported (one of {list(FLOAT_TYPES)})")
    return FLOAT_TYPES[name]


def tensor_to_f32(tensor) -> np.ndarray:
    """torch tensor or numpy array -> flat float32 numpy."""
    if hasattr(tensor, "detach"):
        import torch

        tensor = tensor.detach().cpu().to(torch.float32).numpy()
    return np.ascontiguousarray(tensor, dtype=np.float32).reshape(-1)


def write_tensor(f, tensor, float_type: int) -> int:
    x = tensor_to_f32(tensor)
    t0 = time.time()
    if float_type == FloatType.F32:
        data = x.astype("<f4").tobytes()
    elif float_type == FloatType.F16:
        data = x.astype("<f2").tobytes()
    elif float_type == FloatType.Q40:
        data = quantize_q40(x).tobytes()
    elif float_type == FloatType.Q80:
        data = quantize_q80(x, mode="converter").tobytes()
    else:
        raise ValueError(f"unknown float type {float_type}")
    f.write(data)
    print(f"saved {float_type_name(float_type)} tensor, {len(data)} bytes in {time.time() - t0:.2f}s")
    return len(data)


def write_header(f, header: ModelHeader) -> None:
    write_model_header(f, header)
    for key, value in header.to_kv_pairs():
        print(f"🎓 key {key}: {value}")
