"""The runtime recompile witness (analysis/jitcheck.py,
``DLLAMA_JITCHECK=1``): compile stability proven at runtime.

Layers, mirroring tests/test_lockcheck.py:

- **wiring** — arming, pausing (``warming()``), strict-mode raising,
  the always-on counter, weak sink registration;
- **the serving pin** — a REAL engine + scheduler churn under the
  forced witness: warmup arms it, mixed greedy/sampled requests with a
  shared prefix (the copy_lane path this PR added to warmup) generate
  end to end, and ``jit_compiles_after_warmup`` reads 0 — the
  machine-checked form of "one compiled program per (family, bucket),
  compiled only at warmup";
- **the firing regression** — a deliberately unwarmed family
  (``decode_multi`` horizons with ``multi_step=0`` warmup) makes the
  witness RAISE at the guilty dispatch and the counter record it;
- **the tier-1 fixture pattern** — a subprocess rerun of the serving
  pin with ``DLLAMA_JITCHECK=1`` in the environment (the env path, not
  ``force()``), the test_lockcheck.py recipe.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.analysis import jitcheck
from distributed_llama_multiusers_tpu.analysis.jitcheck import (
    RecompileAfterWarmup,
)
from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture
def witness_on():
    """Force strict mode (fresh sink registry) and restore the
    env-driven default afterwards."""
    jitcheck.force(True, fresh=True)
    try:
        yield
    finally:
        jitcheck.force(None, fresh=True)


@pytest.fixture
def counter_only():
    """Counter armed, strict raising OFF — the production default once
    warmup has run."""
    jitcheck.force(False, fresh=True)
    try:
        yield
    finally:
        jitcheck.force(None, fresh=True)


class _Stats:
    """Minimal EngineStats stand-in for unit tests."""

    def __init__(self):
        import threading

        self.lock = threading.Lock()
        self.jit_compiles_after_warmup = 0


# -- wiring -------------------------------------------------------------------


def test_disabled_by_default():
    assert not jitcheck.enabled()


def test_env_flag_enables(monkeypatch):
    jitcheck.force(None, fresh=False)
    monkeypatch.setenv(jitcheck.ENV_FLAG, "1")
    assert jitcheck.enabled()
    monkeypatch.setenv(jitcheck.ENV_FLAG, "0")
    assert not jitcheck.enabled()


def test_counter_bumps_without_strict(counter_only):
    import jax

    x = jnp.zeros(5)  # the operand's own fill compiles BEFORE arming
    stats = _Stats()
    jitcheck.arm(stats)
    f = jax.jit(lambda x: x * 2)
    f(x)  # compiles: armed, not strict -> counted, no raise
    assert stats.jit_compiles_after_warmup == 1
    f(x)  # executable-cache hit: no event, no bump
    assert stats.jit_compiles_after_warmup == 1


def test_warming_pause_suppresses_counting(counter_only):
    import jax

    x = jnp.zeros(6)
    stats = _Stats()
    jitcheck.arm(stats)
    f = jax.jit(lambda x: x * 3)
    with jitcheck.warming():
        f(x)  # a fresh compile, but paused
    assert stats.jit_compiles_after_warmup == 0


def test_strict_raises_at_the_guilty_call(witness_on):
    import jax

    x = jnp.zeros(7)
    stats = _Stats()
    jitcheck.arm(stats)
    f = jax.jit(lambda x: x * 5)
    with pytest.raises(RecompileAfterWarmup):
        f(x)
    assert stats.jit_compiles_after_warmup >= 1


def test_arm_is_idempotent_and_sinks_are_weak(counter_only):
    import jax

    x = jnp.zeros(9)
    stats = _Stats()
    jitcheck.arm(stats)
    jitcheck.arm(stats)  # no duplicate bumps
    f = jax.jit(lambda x: x * 7)
    f(x)
    assert stats.jit_compiles_after_warmup == 1
    assert jitcheck.armed()


# -- the serving pin ----------------------------------------------------------


def _stack(tiny_model, n_lanes=2):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(
        tiny_model["model"], h, dtype=jnp.float32
    )
    tok = Tokenizer(tiny_model["tokenizer"])
    engine = InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(8, 16)
    )
    return engine, tok


def _churn(engine, tok, n=4, max_tokens=6):
    sched = ContinuousBatchingScheduler(engine, tok)
    warmup_engine(engine, spec=True, multi_step=sched.multi_step)
    sched.start()
    try:
        # mixed traffic over a SHARED prompt: greedy + device-sampled
        # lanes, prefix reuse (the copy_lane program this PR added to
        # warmup), fused admissions into the live chain
        reqs = [
            Request(
                prompt="hello world shared prefix",
                max_tokens=max_tokens,
                temperature=0.0 if i % 2 == 0 else 0.8,
                seed=11 + i,
            )
            for i in range(n)
        ]
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return sched


def test_serving_churn_is_compile_stable_under_witness(tiny_model, witness_on):
    """THE pin: a real serving churn after warmup compiles NOTHING —
    strict mode would have raised at the guilty dispatch, and the
    counter the bench phases bank reads 0.

    Runs under ``DLLAMA_DEQUANT=auto`` (ISSUE 18): with f32 params the
    resolved arithmetic is identical to the default, so the baseline pin
    loses nothing, and the auto serving smoke rides the same churn —
    warmup must freeze the selection table (a live reload would retrace
    every warmed family) and per-site resolution must add no compiles.
    The per-site mode routing itself is pinned under jit in
    tests/test_pallas_q40.py (the BLOCKDOT_MAX_M boundary test)."""
    from distributed_llama_multiusers_tpu.ops import dequant_select, pallas_q40

    dequant_select._reset_for_tests()
    pallas_q40.set_dequant_mode("auto")
    try:
        engine, tok = _stack(tiny_model)
        _churn(engine, tok)
        assert engine.stats.snapshot()["jit_compiles_after_warmup"] == 0
        with pytest.raises(RuntimeError, match="frozen"):
            dequant_select.reload_table()
    finally:
        pallas_q40.set_dequant_mode(None)
        dequant_select._reset_for_tests()


@pytest.fixture(scope="module")
def nospec_engine(tiny_model):
    """ONE engine warmed WITHOUT multi-step horizons (multi_step=0),
    shared by the two unwarmed-family tests below: warmup is the
    expensive part (~10s of CPU compiles), and each test dispatches a
    DIFFERENT horizon, so each still pays — and witnesses — its own
    fresh compile. Tests re-arm after their force(fresh=True) fixture
    clears the sink registry."""
    engine, tok = _stack(tiny_model)
    warmup_engine(engine, spec=False, multi_step=0)
    return engine


def test_witness_fires_on_deliberately_unwarmed_family(
    nospec_engine, witness_on
):
    """The regression the satellite asks for: a family warmup skipped
    (multi-step horizons with multi_step=0) RAISES at its first
    dispatch and the counter records the compile."""
    engine = nospec_engine
    jitcheck.arm(engine.stats)
    z = np.zeros(engine.n_lanes, np.int32)
    with pytest.raises(RecompileAfterWarmup):
        engine.decode_multi(z, z, h=2)
    assert engine.stats.snapshot()["jit_compiles_after_warmup"] >= 1


def test_counter_survives_stats_reset(nospec_engine, counter_only):
    """jit_compiles_after_warmup describes compile stability since
    warmup, not a stats window: reset() must not clear it (a window
    reset hiding a mid-serving recompile would defeat the witness)."""
    engine = nospec_engine
    jitcheck.arm(engine.stats)
    before = engine.stats.snapshot()["jit_compiles_after_warmup"]
    z = np.zeros(engine.n_lanes, np.int32)
    engine.decode_multi(z, z, h=3)  # unwarmed horizon: counts, no raise
    assert engine.stats.snapshot()["jit_compiles_after_warmup"] > before
    engine.stats.reset()
    assert engine.stats.snapshot()["jit_compiles_after_warmup"] > before


# -- the tier-1 fixture pattern (subprocess, env-armed) -----------------------


@pytest.mark.slow  # tier-2: a fresh jax process + full warmup; the
# in-process serving pin above keeps this class covered in tier-1
def test_serving_suite_clean_under_env_jitcheck():
    """Rerun the serving pin in a subprocess with DLLAMA_JITCHECK=1 in
    the ENVIRONMENT (the deployment spelling, exercising the env-flag
    path end to end) — the test_lockcheck.py tier-1 fixture pattern."""
    env = dict(os.environ)
    env["DLLAMA_JITCHECK"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            "tests/test_jitcheck.py",
            "-k", "serving_churn_is_compile_stable",
            "-q", "-p", "no:cacheprovider",
        ],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, (
        f"serving churn recompiled under DLLAMA_JITCHECK=1:\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    )
