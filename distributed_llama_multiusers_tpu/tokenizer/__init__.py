from .tokenizer import Tokenizer
from .chat import (
    ChatTemplateGenerator,
    ChatItem,
    GeneratedChat,
    TokenizerChatStops,
    TemplateType,
    template_type_from_name,
    eos_piece_of,
    chat_generator_for,
)
from .eos import EosDetector, EosResult
from .sampler import Sampler
