"""Paged KV pool: fixed-size KV pages + a ref-counted cross-request
prefix tree — the host-side bookkeeping half of paged attention.

The contiguous layout binds every resident session to one physical lane
plane (``[n_lanes, seq_len, ...]``): a session's KV footprint is seq_len
slots whether it uses them or not, prefix reuse is a whole-lane HBM copy
(``engine.copy_lane``), and finished sessions stay warm only until a new
request happens to claim their lane. This module virtualizes that: the
device holds ONE pool of fixed-size pages (``page_size`` tokens each,
power of two, every layer's K/V for those tokens), each lane maps to
physical pages through a page table, and this class owns the host truth —
the free list, per-page refcounts, and a prefix tree keyed on
token-block content so N concurrent requests sharing a system prompt map
their prefix blocks to the SAME physical pages with zero copies.

Core rules:

- **Granularity** — only FULL blocks enter the tree (a block's content is
  immutable once committed: writes land strictly past the committing
  lane's watermark, so shared pages are never write targets). A partial
  match at the first divergent block is served copy-on-write: ONE page is
  copied (``engine``-side device op, ~page_size tokens x layers — vs
  copy_lane's whole-lane move) and the tail prefill rewrites it from the
  divergence point before any query can read the stale slots.
- **Reservation** — admission charges the lane's whole potential range
  (prompt + max_tokens, clamped to seq_len) up front, so the pipelined
  loop never needs a mid-chain allocation (the device advances positions
  by per-lane spec accept counts the host only learns one step behind —
  a lazy allocator could not keep up without a sync). Unused reserved
  pages return at finish.
- **Parking** — a finished session parks: its tree-registered blocks stay
  resident (refcounted) so chat follow-ups and same-prompt admissions
  hit copy-free, while its non-sharable tail pages free immediately.
  Parked sessions are LRU-evicted under pool pressure (an admission that
  cannot be served from the free list evicts before it sheds): dropped
  sessions rebuild deterministically on next activity by re-prefilling
  from the journaled prompt tokens — resident sessions are bounded by
  journal bytes, not HBM.
- **Exhaustion** — when eviction cannot cover an admission either, the
  pool raises :class:`PoolExhausted`; the scheduler sheds the request
  with a typed retryable 429 (``AdmissionRejected("pool_exhausted")``)
  instead of corrupting another session's pages.

Safety against in-flight junk writes (the pipelined ring dispatches up
to ``depth`` steps past a stop the host has not consumed yet): every
device mutation threads the one donated cache pytree, so all page writes
are totally ordered by dispatch. A freed page re-allocated to a new lane
is only ever READ by that lane after the lane's own (later-dispatched)
writes covered the read frontier, and shared pages only expose content
below the committing session's watermark — the same
overwrite-before-readable invariant the contiguous path relies on.

- **Tiered residency** — between "parked in HBM" (reactivates free) and
  "dropped" (reactivates by re-prefill) sits :class:`HostTier`: a
  bounded (``--kv-host-bytes``, LRU) host-RAM store of swapped page
  payloads keyed by the SAME ``(parent_key, block)`` content-hash chain
  as the prefix tree, so a swapped prefix is still shared — one host
  copy serves every future admission of that chain. Pressure eviction
  deposits each freed committed page into ``_pending_swapouts``; the
  ENGINE drains those (``take_pending_swapouts`` -> device read ->
  ``HostTier.put``) before dispatching any write that could reuse the
  page, and an admission that misses HBM but hits the host tier gets its
  payloads back as ``swapins`` — fresh pages that reactivate with a
  host->device copy instead of a re-prefill. Integrity rides
  :func:`~..disagg.kvtransfer.page_hash` (one serializer with the
  disagg transfer path, no drift): a mismatch on swap-in raises
  :class:`HostTierCorrupt` (request-scoped, entry dropped, prefix tree
  untouched) and the retry re-prefills. ``--kv-host-bytes 0`` disables
  the tier and restores drop-to-rebuild bit-for-bit.

Pure host/stdlib (no jax): the device half (pool arrays, page tables,
the page-copy program) lives in :mod:`runtime.engine`; the scheduler-
level oversubscription tests run this class under MockAsyncEngine
without a backend.
"""

from __future__ import annotations

from collections import OrderedDict
from itertools import islice

from ..disagg.kvtransfer import page_hash
from ..lockcheck import make_lock

# root key of the prefix tree; node keys are (parent_key, block_tokens)
# tuples, so the dict hash IS the block-content hash chain and two
# different prefixes can never collide into one node
_ROOT = ()

DEFAULT_PAGE_SIZE = 64
DEFAULT_MAX_PARKED = 64
# how many sibling blocks the divergent-block COW probe scans (the tree
# fans out per distinct block content; an unbounded scan under the pool
# lock would let adversarial traffic make every admission O(children))
_COW_SCAN_CAP = 16


class PoolExhausted(RuntimeError):
    """Admission could not reserve its pages: even evicting every parked
    session would not free enough — the pool is pinned by active lanes.
    Raised WITHOUT evicting (the parked prefix cache survives the shed,
    so retrying 429 clients cannot hold it empty under pressure). The
    scheduler maps this to a typed retryable shed (HTTP 429), never a
    500."""

    def __init__(self, need: int, free: int, total: int,
                 host_tier_full: bool = False):
        self.pages_needed = need
        self.pages_free = free
        self.pages_total = total
        # whether the host swap tier was enabled AND at budget when the
        # shed fired: the scheduler sheds "host_tier_full" instead of
        # "pool_exhausted" so dashboards can tell "raise --kv-host-bytes"
        # apart from "raise --kv-pool-pages"
        self.host_tier_full = host_tier_full
        super().__init__(
            f"kv page pool exhausted: admission needs {need} pages, "
            f"{free}/{total} free and parked-session eviction cannot "
            "cover the rest"
        )


class HostTierCorrupt(ValueError):
    """A swapped page's payload failed its integrity re-hash on the way
    back in. ValueError family on purpose: the scheduler treats it as a
    request-scoped failure (HTTP 4xx/typed stream error, breaker stays
    closed) — the corrupt entry is dropped from the tier before raising,
    the prefix tree was never touched, and the request's retry misses
    the tier and re-prefills deterministically from the prompt."""

    def __init__(self, detail: str = ""):
        super().__init__(
            "host-tier kv page failed integrity verification"
            + (f": {detail}" if detail else "")
        )


def blocks_for(n_tokens: int, page_size: int) -> int:
    """Pages needed to hold ``n_tokens`` KV slots."""
    return max(0, -(-int(n_tokens) // int(page_size)))


class HostTier:
    """Bounded host-RAM store of swapped KV page payloads — the middle
    residency tier between "parked in HBM" and "dropped".

    Entries are keyed by the prefix tree's node key (the
    ``(parent_key, block)`` content-hash chain), so the tier IS a
    shadow of the tree for pages the pool had to free: one host copy
    serves every future admission that walks the same chain, exactly
    like a resident parked page serves N sharers. The byte budget is
    LRU-enforced at ``put``; a hit refreshes recency and does NOT
    remove the entry (shared by design — removal happens only by LRU
    pressure, :meth:`discard`, :meth:`clear`, or a failed integrity
    re-hash). Every payload is hashed at ``put`` and re-verified at
    ``get`` with :func:`~..disagg.kvtransfer.page_hash` — the same
    canonical framing the disagg transfer bundles use, so the two
    serializers cannot drift.

    Own lock (``HostTier._lock``): the engine's drain runs device reads
    between ``put`` calls, and /stats reads the gauges from HTTP
    threads; the pool may call in while holding ``KVPagePool._lock``
    (pool -> tier is the one sanctioned nesting order — the tier never
    calls back into the pool)."""

    # dlint guarded-by declaration (analysis/lock_check.py): all tier
    # state may only be touched holding `_lock`
    _dlint_guarded_by = {
        ("_lock",): (
            "_swapped", "_bytes",
            "hits", "misses", "evicted", "full_drops", "corrupt_drops",
            "stored",
        ),
    }

    # dlint resource-lifecycle declaration (analysis/resourcemodel.py):
    # the release half of the host-page kind — pending swap-outs the
    # engine took from the pool (``take_pending_swapouts`` acquires)
    # must each land in ``put`` (stored) or ``discard`` (dropped:
    # device read failed, tier disabled mid-flight, containment).
    _dlint_releases = {"host-page": ("put", "discard")}

    def __init__(self, budget_bytes: int, page_size: int):
        self.budget_bytes = max(0, int(budget_bytes))
        self.page_size = int(page_size)
        self._lock = make_lock("HostTier._lock")
        # node key -> (payload bytes, integrity hash); OrderedDict order
        # IS the LRU (oldest first)
        self._swapped: "OrderedDict[tuple, tuple[bytes, str]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evicted = 0
        self.full_drops = 0  # payloads refused at put (oversize/disabled)
        self.corrupt_drops = 0  # entries dropped by a failed re-hash
        self.stored = 0

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    def full(self) -> bool:
        """Whether the tier is at (or over) its byte budget — the
        host-tier-full half of the shed-reason distinction."""
        with self._lock:
            return self.enabled and self._bytes >= self.budget_bytes

    def put(self, node_key: tuple, blk_tokens, payload: bytes) -> bool:
        """Store one swapped page's payload under its tree node key.
        Hashes the payload (the exporter-side half of the integrity
        frame), refreshes recency on a re-put of a known key, and
        LRU-evicts until the byte budget holds. Returns whether the
        payload is resident after the call — ``False`` means dropped
        (tier disabled, or the payload alone exceeds the budget)."""
        blk = tuple(int(t) for t in blk_tokens)
        data = bytes(payload)
        h = page_hash(self.page_size, blk, data)
        with self._lock:
            if not self.enabled or len(data) > self.budget_bytes:
                self.full_drops += 1
                return False
            prior = self._swapped.pop(node_key, None)
            if prior is not None:
                self._bytes -= len(prior[0])
            while self._swapped and self._bytes + len(data) > self.budget_bytes:
                _, (old, _h) = self._swapped.popitem(last=False)
                self._bytes -= len(old)
                self.evicted += 1
            self._swapped[node_key] = (data, h)
            self._bytes += len(data)
            self.stored += 1
            return True

    def get(self, node_key: tuple, blk_tokens) -> bytes | None:
        """Look up a swapped page by tree node key. A hit re-verifies
        the payload against its stored hash and refreshes LRU recency
        (the entry STAYS — one host copy serves N admissions); a failed
        re-hash drops the entry and raises :class:`HostTierCorrupt`
        (request-scoped — the caller has not mutated anything yet)."""
        blk = tuple(int(t) for t in blk_tokens)
        with self._lock:
            entry = self._swapped.get(node_key)
            if entry is None:
                self.misses += 1
                return None
            data, want = entry
            if page_hash(self.page_size, blk, data) != want:
                del self._swapped[node_key]
                self._bytes -= len(data)
                self.corrupt_drops += 1
                raise HostTierCorrupt(
                    f"node at depth {_key_depth(node_key)} "
                    f"({len(data)} bytes) — entry dropped, request "
                    "retry will re-prefill"
                )
            self._swapped.move_to_end(node_key)
            self.hits += 1
            return data

    def discard(self, node_key: tuple) -> None:
        """Drop an entry if present (idempotent) — the release path for
        a pending swap-out whose device read failed, and the disposal
        half of containment."""
        with self._lock:
            entry = self._swapped.pop(node_key, None)
            if entry is not None:
                self._bytes -= len(entry[0])

    def clear(self) -> int:
        """Drop every entry (containment / the bench's rebuild lever —
        without this, drop_parked would still reactivate via the tier).
        Returns how many entries were dropped."""
        with self._lock:
            n = len(self._swapped)
            self._swapped.clear()
            self._bytes = 0
            return n

    def stats(self) -> dict:
        """Tier pressure snapshot (one lock hold); merged into the
        pool's ``stats()`` so every field rides the /stats -> /metrics
        bridge as a ``dllama_stats_pool_*`` gauge."""
        with self._lock:
            return {
                "pool_host_pages": len(self._swapped),
                "pool_host_bytes": self._bytes,
                "pool_host_budget_bytes": self.budget_bytes,
                "pool_host_hits": self.hits,
                "pool_host_misses": self.misses,
                "pool_host_evicted": self.evicted,
                "pool_host_full_drops": self.full_drops,
                "pool_host_corrupt": self.corrupt_drops,
                "pool_host_stored": self.stored,
            }


def _key_depth(key: tuple) -> int:
    """Chain depth of a prefix-tree node key (diagnostics only)."""
    d = 0
    while key != _ROOT and isinstance(key, tuple) and len(key) == 2:
        key = key[0]
        d += 1
    return d


class KVPagePool:
    """Host bookkeeping for a device-resident paged KV pool.

    All mutation happens on the scheduler loop thread; ``stats()`` is
    read from HTTP threads — every access holds ``_lock`` (machine-
    checked via ``_dlint_guarded_by``). The pool never touches a device
    value: ``admit`` returns the physical block list + the page-copy ops
    for the ENGINE to apply (and, on a pod root, to broadcast)."""

    # dlint guarded-by declaration (analysis/lock_check.py): all pool
    # state may only be touched holding `_lock` (or in __init__ /
    # *_locked methods). Machine-checked by `make lint`.
    _dlint_guarded_by = {
        ("_lock",): (
            "_free", "_ref", "_nodes", "_page_key", "_children",
            "_lane_blocks", "_lane_reg", "_lane_tip",
            "_parked", "_parked_pages", "_park_refs", "_park_seq",
            "_park_index", "_pending_swapouts",
            "admits", "prefix_admits", "prefix_tokens_shared",
            "cow_copies", "parked_evicted", "exhausted_sheds",
            "parked_total", "pool_resets",
            "adopts", "adopted_pages_fresh",
            "swap_in_admits", "host_pages_swapped_in",
        ),
    }

    # dlint resource-lifecycle declaration (analysis/resourcemodel.py):
    # lane page ownership. ``admit``/``adopt`` hand lane-held pages to
    # the caller; every exit path must reach ``finish`` (park or free),
    # ``release``/``drop_parked`` (park holds), or ``reset``. Checked by
    # resource-balance; witnessed at runtime via ``pool_pages_in_use``
    # (analysis/leakcheck.py, DLLAMA_LEAKCHECK=1). The host-page kind is
    # the swap tier's half: ``take_pending_swapouts`` hands the engine
    # the deposited (node_key, block, page) triples, and each must land
    # in ``HostTier.put`` or ``HostTier.discard`` — witnessed at runtime
    # via ``pool_swap_pending`` (scheduler.leak_counts).
    _dlint_acquires = {
        "kv-page": ("admit", "adopt"),
        "host-page": ("take_pending_swapouts",),
    }
    _dlint_releases = {"kv-page": ("finish", "release", "drop_parked", "reset")}

    def __init__(
        self,
        n_pages: int,
        page_size: int = DEFAULT_PAGE_SIZE,
        n_lanes: int = 8,
        blocks_per_lane: int | None = None,
        max_parked: int = DEFAULT_MAX_PARKED,
        host_bytes: int = 0,
    ):
        if page_size <= 0 or (page_size & (page_size - 1)) != 0:
            raise ValueError(
                f"page_size must be a power of two, got {page_size}"
            )
        if n_pages <= 0:
            raise ValueError(f"n_pages must be positive, got {n_pages}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.n_lanes = int(n_lanes)
        # table width: how many blocks one lane can map (defaults to a
        # full-seq_len lane's worth when the engine builds the pool)
        self.blocks_per_lane = int(blocks_per_lane or n_pages)
        self.max_parked = max(0, int(max_parked))
        # built via make_lock so the runtime lock-order witness
        # (DLLAMA_LOCKCHECK=1) can wrap it; literal cross-checked by dlint
        self._lock = make_lock("KVPagePool._lock")
        # LIFO free stack: recently freed pages are re-used first (their
        # device buffers are the most likely to still be resident-hot)
        self._free: list[int] = list(range(self.n_pages))
        self._ref = [0] * self.n_pages
        # prefix tree: node key -> physical page; key = (parent_key,
        # tuple(block tokens)) chains content, so a lookup walk is one
        # dict get per block. _children mirrors it parent-first for the
        # divergent-block COW probe; _page_key inverts it for removal
        # when a page's refcount hits zero.
        self._nodes: dict[tuple, int] = {}
        self._page_key: dict[int, tuple] = {}
        self._children: dict[tuple, dict[tuple, int]] = {}
        # per-lane mapping: physical pages in block order, how many
        # blocks the lane has registered into the tree, and the tree key
        # of its registration tip (the chain grows from there)
        self._lane_blocks: list[list[int]] = [[] for _ in range(self.n_lanes)]
        self._lane_reg = [0] * self.n_lanes
        self._lane_tip: list[tuple] = [_ROOT for _ in range(self.n_lanes)]
        # parked sessions: park id -> registered block list; OrderedDict
        # order IS the LRU (oldest first). _parked_pages counts DISTINCT
        # physical pages pinned by parking (shared pages once, not once
        # per holder — the gauge means real pool occupancy, and LOWER
        # pages-per-parked-session = more overlap); _park_refs is the
        # per-page park-hold count behind that dedup.
        self._parked: "OrderedDict[int, list[int]]" = OrderedDict()
        self._park_refs: dict[int, int] = {}
        # block-list identity -> park id: a re-park of an IDENTICAL
        # chain refreshes recency in one slot instead of flooding the
        # LRU with duplicate holders of the same pages (one repetitive
        # client would otherwise evict every other parked prefix)
        self._park_index: dict[tuple, int] = {}
        self._parked_pages = 0
        self._park_seq = 0
        # host swap tier (disabled at host_bytes=0 — every tier branch
        # below gates on enabled, so 0 restores drop-to-rebuild exactly)
        # and the swap-out staging list: pressure eviction deposits
        # (node_key, block_tokens, page) here for pages whose last ref
        # just drained; the ENGINE drains it (take_pending_swapouts ->
        # device read -> HostTier.put) before dispatching any write that
        # could reuse the page — the donated-pytree ordering makes the
        # read see pre-eviction bytes. Carries the node key because by
        # drain time the page's tree entry is gone.
        self.host_tier = HostTier(host_bytes, self.page_size)
        self._pending_swapouts: list[tuple[tuple, tuple, int]] = []
        # counters (stats() snapshots them for /stats -> /metrics)
        self.admits = 0
        self.prefix_admits = 0
        self.prefix_tokens_shared = 0
        self.cow_copies = 0
        self.parked_evicted = 0  # drop-rebuild: sessions whose pages were
        # reclaimed under pressure; their next activity re-prefills from
        # the journaled prompt (deterministically byte-identical)
        self.exhausted_sheds = 0
        self.parked_total = 0
        self.pool_resets = 0
        self.adopts = 0  # disagg: chains adopted from a peer replica
        self.adopted_pages_fresh = 0  # pages that needed a payload import
        self.swap_in_admits = 0  # admissions served partly from the tier
        self.host_pages_swapped_in = 0  # pages reactivated by host copy

    @classmethod
    def for_seq_len(
        cls,
        seq_len: int,
        n_lanes: int,
        page_size: int = DEFAULT_PAGE_SIZE,
        pool_pages: int | None = None,
        max_parked: int = DEFAULT_MAX_PARKED,
        host_bytes: int = 0,
    ) -> "KVPagePool":
        """THE pool-construction recipe, shared by the real engine and
        MockAsyncEngine's paged mode so the two cannot drift: validate
        the page size (power of two), shrink it to fit short contexts
        (tiny test configs) while staying a power of two, and default
        the pool to the contiguous layout's exact footprint
        (``n_lanes`` x blocks-per-full-lane) — oversubscription comes
        from sessions reserving only what they can use, never from a
        bigger pool. Callers derive the device/table shapes from the
        result (``page_size``, ``blocks_per_lane``, ``n_pages``)."""
        bs = int(page_size)
        if bs <= 0 or bs & (bs - 1):
            raise ValueError(
                f"kv_page_size must be a positive power of two, "
                f"got {page_size}"
            )
        while bs > seq_len:
            bs //= 2
        n_blocks = blocks_for(seq_len, bs)
        # None = not set (contiguous-footprint default); an explicit 0 or
        # negative must die in __init__'s validation, not silently become
        # the default pool
        n_pages = int(n_lanes * n_blocks if pool_pages is None
                      else pool_pages)
        return cls(n_pages, bs, n_lanes, blocks_per_lane=n_blocks,
                   max_parked=max_parked, host_bytes=host_bytes)

    # -- admission -----------------------------------------------------------

    def admit(
        self,
        lane: int,
        tokens: list[int],
        reserve_tokens: int,
        min_share_tokens: int = 1,
    ) -> tuple[int, list[int], list[tuple[int, int]],
               list[tuple[int, bytes]]]:
        """Reserve lane ``lane``'s pages for a request whose prompt is
        ``tokens`` and whose whole potential range is ``reserve_tokens``
        KV slots. Returns ``(start, blocks, copies, swapins)``:

        - ``start`` — prompt tokens already resident via sharing: full
          blocks by refcount bump, host-tier full blocks swapped back
          in, plus up to one partial block served copy-on-write. The
          caller prefills only ``tokens[start:]`` (always >= 1 token,
          the prefix-cache rule).
        - ``blocks`` — the lane's physical pages in block order (shared
          prefix pages first), for the device page table.
        - ``copies`` — ``(src_page, dst_page)`` device copies the engine
          must apply BEFORE the tail prefill (the COW at the divergent
          block; at most one).
        - ``swapins`` — ``(page, payload)`` host->device page writes the
          engine must apply BEFORE the tail prefill: the prompt's chain
          continued in the HOST TIER past the resident prefix, so those
          blocks reactivate by copy instead of re-prefill. The pages are
          already registered back into the prefix tree (the next
          admission shares them resident, zero copies).

        ``min_share_tokens`` gates sharing like the contiguous path's
        ``prefix_min_tokens`` (<= 0 disables sharing entirely). Raises
        :class:`PoolExhausted` when the reservation cannot be served
        even after evicting every parked session, and
        :class:`HostTierCorrupt` (BEFORE any pool mutation — the tree
        is never poisoned by a bad swapped payload) when a host-tier
        hit fails its integrity re-hash."""
        with self._lock:
            self._release_locked(lane)  # defensive: lane must start empty
            bs = self.page_size
            max_reuse = len(tokens) - 1  # >= 1 token must prefill
            shared_pages: list[int] = []
            key = _ROOT
            if min_share_tokens > 0:
                while (len(shared_pages) + 1) * bs <= max_reuse:
                    blk = tuple(tokens[len(shared_pages) * bs:
                                       (len(shared_pages) + 1) * bs])
                    page = self._nodes.get((key, blk))
                    if page is None:
                        break
                    key = (key, blk)
                    shared_pages.append(page)
            # the chain may CONTINUE in the host tier past the resident
            # frontier: swapped blocks reactivate into fresh pages by a
            # host->device copy instead of a re-prefill. The walk runs
            # before any ref/eviction side effect, so a HostTierCorrupt
            # out of get() leaves the pool exactly as it found it.
            hbm_key = key
            swap_meta: list[tuple[tuple, bytes]] = []  # (block, payload)
            if min_share_tokens > 0 and self.host_tier.enabled:
                while (len(shared_pages) + len(swap_meta) + 1) * bs <= max_reuse:
                    i = len(shared_pages) + len(swap_meta)
                    blk = tuple(tokens[i * bs: (i + 1) * bs])
                    payload = self.host_tier.get((key, blk), blk)
                    if payload is None:
                        break
                    key = (key, blk)
                    swap_meta.append((blk, payload))
            start = (len(shared_pages) + len(swap_meta)) * bs
            # divergent-block COW probe: the best sibling block sharing a
            # leading run with our next (possibly partial) block
            cow_src = -1
            cow_len = 0
            if min_share_tokens > 0 and start < max_reuse:
                want = tokens[start: min(start + bs, max_reuse)]
                kids = self._children.get(key)
                if kids and want:
                    # islice, not a list copy: the cap exists so wide
                    # fan-out can't make admissions O(children) under
                    # the pool lock — copying the dict first would
                    for blk, page in islice(kids.items(), _COW_SCAN_CAP):
                        p = 0
                        lim = min(len(blk), len(want))
                        while p < lim and blk[p] == want[p]:
                            p += 1
                        if p > cow_len:
                            cow_src, cow_len = page, p
            if start + cow_len < max(1, min_share_tokens):
                # below the sharing threshold: admit fully private (key
                # included — a stale tip would make commit() register
                # this lane's blocks under the matched chain, poisoning
                # future walks with wrong-position KV)
                shared_pages = []
                swap_meta = []
                start = 0
                cow_src, cow_len = -1, 0
                key = _ROOT
                hbm_key = _ROOT
            n_blocks = blocks_for(
                max(reserve_tokens, len(tokens) + 1), bs
            )
            n_blocks = min(n_blocks, self.blocks_per_lane)
            if n_blocks > self.n_pages:
                # structurally unservable (an explicitly undersized
                # --kv-pool-pages): even with every parked session and
                # every other lane evicted the pool cannot hold this
                # reservation, so the retryable PoolExhausted shed would
                # have the client back off and re-probe forever — each
                # probe destructively evicting parked prefixes. ValueError
                # is the scheduler's request-scoped validation class
                # (client error, breaker closed); raised BEFORE any
                # ref/eviction side effect.
                raise ValueError(
                    f"kv page reservation needs {n_blocks} pages but the "
                    f"pool holds {self.n_pages} total: lower the "
                    "request's max_tokens/prompt or raise --kv-pool-pages"
                )
            need = n_blocks - len(shared_pages)
            # take the shared refs (and a COW-source pin) BEFORE any
            # eviction: the parked holders may be the ONLY refs on the
            # pages this admission matched, and evicting them would free
            # pages we are about to map (the free-list pop could then
            # hand the same physical page back as a fresh block)
            for p in shared_pages:
                self._ref[p] += 1
            cow_pinned = cow_src >= 0
            if cow_pinned:
                self._ref[cow_src] += 1
            if len(self._free) < need:
                # evict only when eviction can actually serve this
                # admission: a shed that had first drained the parked LRU
                # would leave retrying 429 clients holding the prefix
                # cache empty for as long as the pool stays pinned — the
                # retry-probe destruction the structural guard above
                # stops for need > n_pages, generalized to transient
                # pressure. A page is evictable iff park holds are its
                # ONLY refs (shared/pinned pages stay resident anyway).
                evictable = sum(
                    1 for p, held in self._park_refs.items()
                    if self._ref[p] == held
                )
                if len(self._free) + evictable < need:
                    self.exhausted_sheds += 1
                    for p in shared_pages:  # undo before shedding
                        self._deref_locked(p)
                    if cow_pinned:
                        self._deref_locked(cow_src)
                    raise PoolExhausted(
                        need, len(self._free), self.n_pages,
                        host_tier_full=self.host_tier.full(),
                    )
                self._evict_parked_locked(need - len(self._free))
            if len(self._free) < need:
                # backstop (the sufficiency check above should make this
                # unreachable): never hand out a short reservation
                self.exhausted_sheds += 1
                for p in shared_pages:
                    self._deref_locked(p)
                if cow_pinned:
                    self._deref_locked(cow_src)
                raise PoolExhausted(
                    need, len(self._free), self.n_pages,
                    host_tier_full=self.host_tier.full(),
                )
            fresh = [self._free.pop() for _ in range(need)]
            for p in fresh:
                self._ref[p] = 1
            if cow_pinned:
                # the pin only had to survive eviction: the device copy
                # is dispatched synchronously with this admission, before
                # any later admission's writes can reuse the page
                self._deref_locked(cow_src)
            copies: list[tuple[int, int]] = []
            if cow_src >= 0 and cow_len > 0 and len(fresh) > len(swap_meta):
                # COW only fires at the HBM frontier (a tier-extended tip
                # is not a tree node, so the sibling probe found nothing)
                # — swap_meta is empty here and the dst is fresh[0], but
                # index past the swap-in pages anyway so the two claims
                # can never alias if either walk ever changes
                copies.append((cow_src, fresh[len(swap_meta)]))
                start += cow_len
                self.cow_copies += 1
            # swapped blocks land in the LEADING fresh pages and register
            # straight back into the prefix tree (the same duplicate rule
            # as commit(): the walk just proved these nodes absent, and
            # each next node chains from the one we create) — the next
            # same-prefix admission shares them RESIDENT, zero copies.
            # The caller must apply the (page, payload) writes before the
            # tail prefill, exactly like the COW copies.
            swapins: list[tuple[int, bytes]] = []
            reg_key = hbm_key
            for j, (blk, payload) in enumerate(swap_meta):
                page = fresh[j]
                child = (reg_key, blk)
                if child not in self._nodes:
                    self._nodes[child] = page
                    self._page_key[page] = child
                    self._children.setdefault(reg_key, {})[blk] = page
                reg_key = child
                swapins.append((page, payload))
            blocks = shared_pages + fresh
            self._lane_blocks[lane] = blocks
            self._lane_reg[lane] = len(shared_pages) + len(swap_meta)
            self._lane_tip[lane] = key
            self.admits += 1
            if swapins:
                self.swap_in_admits += 1
                self.host_pages_swapped_in += len(swapins)
            if start > 0:
                self.prefix_admits += 1
                self.prefix_tokens_shared += start
            return start, list(blocks), copies, swapins

    def commit(self, lane: int, tokens: list[int]) -> None:
        """Register lane ``lane``'s newly completed full blocks into the
        prefix tree. ``tokens`` is the lane's committed history (prompt +
        consumed generated tokens); idempotent and incremental — call it
        after every commit point, it only walks blocks not yet
        registered. Duplicate content (another session registered the
        identical chain first) keeps the existing node: future sharers
        land on the first copy, ours stays private until it frees."""
        with self._lock:
            bs = self.page_size
            blocks = self._lane_blocks[lane]
            reg = self._lane_reg[lane]
            n_full = len(tokens) // bs
            key = self._lane_tip[lane]
            while reg < n_full and reg < len(blocks):
                blk = tuple(tokens[reg * bs: (reg + 1) * bs])
                child = (key, blk)
                if child not in self._nodes:
                    page = blocks[reg]
                    self._nodes[child] = page
                    self._page_key[page] = child
                    self._children.setdefault(key, {})[blk] = page
                key = child
                reg += 1
            self._lane_reg[lane] = reg
            self._lane_tip[lane] = key

    # -- disaggregated prefill: chain export / adoption ----------------------

    def chain_pages(self, tokens: list[int]) -> list[tuple[tuple, int]]:
        """The longest registered prefix chain over ``tokens``'s FULL
        blocks, as ``(block_tokens, physical_page)`` pairs in chain
        order — the export surface for KV-page transfer (disagg/
        kvtransfer.py). Only committed tree nodes are visible: a lane's
        partial tail block and unshared reservation never leave the
        replica, which is exactly the immutability rule that makes the
        exported bytes stable while the source lane keeps decoding."""
        with self._lock:
            bs = self.page_size
            out: list[tuple[tuple, int]] = []
            key = _ROOT
            for i in range(len(tokens) // bs):
                blk = tuple(tokens[i * bs: (i + 1) * bs])
                page = self._nodes.get((key, blk))
                if page is None:
                    break
                key = (key, blk)
                out.append((blk, page))
            return out

    def adopt(self, token_blocks: list) -> tuple[list[int], list[tuple[int, int]]]:
        """Adopt a transferred block chain into THIS pool's prefix tree.
        ``token_blocks`` is the chain's full blocks (page_size tokens
        each) in order. Returns ``(pages, fresh)``:

        - ``pages`` — the chain's physical pages here, in block order;
        - ``fresh`` — ``(block_index, page)`` pairs for blocks that had
          no local node and were newly allocated: ONLY these need their
          KV payload imported (engine ``import_kv_page``). Blocks the
          local tree already held are reused by refcount — adopting a
          chain a replica partly knows moves only the missing suffix.

        The whole chain is pinned by a park entry (the same LRU slot a
        ``finish(park=True)`` would create, identical-chain dedup
        included), so the adopted prefix survives until a real admission
        shares it or LRU pressure evicts it — refcount-correct by
        construction: each chain page carries exactly one park-held ref,
        like any parked session. Raises :class:`PoolExhausted` WITHOUT
        mutating when free + evictable-parked pages cannot cover the
        missing suffix, and ``ValueError`` for malformed blocks or a
        parking-disabled pool (nothing would pin the adopted pages)."""
        with self._lock:
            bs = self.page_size
            if self.max_parked <= 0:
                raise ValueError(
                    "adopt needs parking enabled (max_parked > 0): a "
                    "parkless pool would free the adopted pages at once"
                )
            chain = [tuple(blk) for blk in token_blocks]
            if not chain:
                raise ValueError("adopt: empty block chain")
            if any(len(blk) != bs for blk in chain):
                raise ValueError(
                    f"adopt: every block must hold exactly {bs} tokens "
                    "(full committed blocks only cross replicas)"
                )
            # walk the chain over the local tree: reused prefix first
            key = _ROOT
            pages: list[int] = []
            for blk in chain:
                page = self._nodes.get((key, blk))
                if page is None:
                    break
                key = (key, blk)
                pages.append(page)
            need = len(chain) - len(pages)
            # sufficiency BEFORE any mutation (the admit() rule): a shed
            # must leave the pool exactly as it found it
            if len(self._free) < need:
                evictable = sum(
                    1 for p, held in self._park_refs.items()
                    if self._ref[p] == held
                )
                if len(self._free) + evictable < need:
                    self.exhausted_sheds += 1
                    raise PoolExhausted(need, len(self._free), self.n_pages)
            # pin reused pages BEFORE eviction — parked holders may be
            # the only refs on the very prefix this adoption extends
            for p in pages:
                self._ref[p] += 1
            if len(self._free) < need:
                self._evict_parked_locked(need - len(self._free))
            if len(self._free) < need:  # backstop: undo and shed
                for p in pages:
                    self._deref_locked(p)
                self.exhausted_sheds += 1
                raise PoolExhausted(need, len(self._free), self.n_pages)
            fresh: list[tuple[int, int]] = []
            for j in range(len(pages), len(chain)):
                p = self._free.pop()
                self._ref[p] = 1
                blk = chain[j]
                child = (key, blk)
                self._nodes[child] = p
                self._page_key[p] = child
                self._children.setdefault(key, {})[blk] = p
                key = child
                pages.append(p)
                fresh.append((j, p))
            # park the whole chain: the operation's refs transfer to the
            # park holder (finish(park=True)'s accounting, dedup included)
            existing = self._park_index.get(tuple(pages))
            if existing is not None:
                self._parked.move_to_end(existing)
                for p in pages:
                    self._deref_locked(p)
            else:
                self._park_seq += 1
                self._parked[self._park_seq] = list(pages)
                self._park_index[tuple(pages)] = self._park_seq
                for p in pages:
                    if self._park_refs.get(p, 0) == 0:
                        self._parked_pages += 1
                    self._park_refs[p] = self._park_refs.get(p, 0) + 1
                while len(self._parked) > self.max_parked:
                    self._evict_oldest_locked()
            self.parked_total += 1
            self.adopts += 1
            self.adopted_pages_fresh += len(fresh)
            return list(pages), fresh

    # -- release / parking ---------------------------------------------------

    def finish(self, lane: int, park: bool = True) -> bool:
        """Release lane ``lane``'s mapping at request end. ``park=True``
        keeps the session's tree-registered blocks resident (refcounted,
        LRU-bounded) so follow-ups share copy-free, and frees the
        non-sharable tail (partial block + unused reservation)
        immediately; a re-park of an IDENTICAL chain refreshes the
        existing entry's recency instead of adding a duplicate holder
        (one repetitive client occupies one LRU slot, not max_parked);
        blocks another lane registered first (duplicate content) back no
        tree node and free rather than park as dead residency;
        ``park=False`` frees everything (the failure path — the cache
        contents are not trusted). Returns whether the lane actually
        held pages: callers skip the device-side table unmap (and, on
        pods, the OP_KV_TABLE broadcast) otherwise — the exhaustion-
        shed reject path releases lanes that never mapped anything, and
        overload rejects must stay host-only cheap."""
        with self._lock:
            blocks = self._lane_blocks[lane]
            if not blocks:
                self._clear_lane_locked(lane)
                return False
            keep: list[int] = []
            if park and self.max_parked > 0:
                for p in blocks[: self._lane_reg[lane]]:
                    if p in self._page_key:
                        keep.append(p)
                    else:
                        # duplicate-content block: another lane registered
                        # the identical chain first, so this page backs no
                        # tree node — no future walk can reach it, and
                        # parking it would be dead residency that evicts
                        # genuinely sharable sessions under pressure
                        self._deref_locked(p)
                for p in blocks[self._lane_reg[lane]:]:
                    self._deref_locked(p)
            else:
                for p in blocks:
                    self._deref_locked(p)
            if keep:
                existing = self._park_index.get(tuple(keep))
                if existing is not None:
                    # identical chain already parked: refresh its LRU
                    # recency and release the lane's (now redundant)
                    # refs — the existing entry's park holds pin the
                    # pages, and a repeat client occupies ONE slot
                    self._parked.move_to_end(existing)
                    for p in keep:
                        self._deref_locked(p)
                else:
                    self._park_seq += 1
                    self._parked[self._park_seq] = keep
                    self._park_index[tuple(keep)] = self._park_seq
                    for p in keep:
                        if self._park_refs.get(p, 0) == 0:
                            self._parked_pages += 1
                        self._park_refs[p] = self._park_refs.get(p, 0) + 1
                    while len(self._parked) > self.max_parked:
                        self._evict_oldest_locked()
                self.parked_total += 1
            self._clear_lane_locked(lane)
            return True

    def release(self, lane: int) -> None:
        """Free lane ``lane``'s mapping without parking (idempotent)."""
        with self._lock:
            self._release_locked(lane)

    def drop_parked(self) -> int:
        """Evict every parked session WITHOUT staging swap-outs (the
        test/benchmark lever for the park -> drop -> journal-rebuild
        round trip — swapping here would turn the rebuild measurement
        into a swap-in measurement). Returns how many sessions were
        dropped."""
        with self._lock:
            n = len(self._parked)
            while self._parked:
                self._evict_entry_locked(next(iter(self._parked)),
                                         swap=False)
            return n

    def swap_out_parked(self) -> int:
        """Evict every parked session WITH swap-out staging (the bench's
        swap-tier lever; pressure eviction does the same organically).
        Returns how many sessions were evicted; the caller must drain
        the staged pages through the engine (``drain_kv_swapouts``)."""
        with self._lock:
            n = len(self._parked)
            while self._parked:
                self._evict_oldest_locked()
            return n

    def take_pending_swapouts(self) -> list[tuple[tuple, tuple, int]]:
        """Hand the engine the staged swap-outs — ``(node_key,
        block_tokens, page)`` triples whose pages just freed under
        pressure (one lock hold, clears the staging list). The host-page
        ACQUIRE: every triple must reach ``HostTier.put`` or
        ``HostTier.discard``. The caller must apply the device reads
        BEFORE dispatching any write that could reuse the pages (the
        donated-pytree ordering guarantees the read still sees the
        pre-eviction bytes)."""
        with self._lock:
            out = self._pending_swapouts
            self._pending_swapouts = []
            return out

    def reset(self) -> None:
        """Containment: drop every lane mapping, every parked session and
        every tree node — after an engine-scoped failure the device pool
        contents are not trusted, so nothing may be shared from them."""
        with self._lock:
            for lane in range(self.n_lanes):
                self._clear_lane_locked(lane)
            # parked sessions drain WITHOUT counting parked_evicted:
            # that gauge means LRU pressure (drop-rebuild); containment
            # is already counted by pool_resets
            self._parked.clear()
            # anything still referenced would be a bookkeeping leak: the
            # reset is the last resort, start from a clean pool
            self._nodes.clear()
            self._page_key.clear()
            self._children.clear()
            self._free = list(range(self.n_pages))
            self._ref = [0] * self.n_pages
            self._park_refs.clear()
            self._park_index.clear()
            self._parked_pages = 0
            # staged swap-outs are DISCARDED, not stored (their device
            # bytes are exactly what containment distrusts), and the
            # tier itself clears — nothing may be shared from before
            # the failure, host copies included
            self._pending_swapouts = []
            self.host_tier.clear()
            self.pool_resets += 1

    # -- introspection -------------------------------------------------------

    def table_row(self, blocks: list[int]) -> list[int]:
        """One lane's page-table row: physical pages in block order,
        padded to ``blocks_per_lane`` with the ``n_pages`` unmapped
        sentinel — THE row-encoding recipe, shared by the engine and
        MockAsyncEngine so the sentinel value and layout cannot drift.
        No lock: reads only immutable pool geometry."""
        row = [self.n_pages] * self.blocks_per_lane
        row[: len(blocks)] = blocks
        return row

    def lane_blocks(self, lane: int) -> list[int]:
        with self._lock:
            return list(self._lane_blocks[lane])

    def page_key(self, page: int) -> tuple | None:
        """The prefix-tree node key page ``page`` backs (``None`` for
        pages holding no committed block) — a pure function of the block
        CONTENT chain, which is what lets MockAsyncEngine derive a
        content-canonical page payload for the disagg integrity hashes."""
        with self._lock:
            return self._page_key.get(int(page))

    def pages_free(self) -> int:
        with self._lock:
            return len(self._free)

    def parked_sessions(self) -> int:
        with self._lock:
            return len(self._parked)

    def stats(self) -> dict:
        """Point-in-time pool pressure snapshot (one lock hold); every
        field is bridged to /metrics as a ``dllama_stats_*`` gauge via
        the /stats bridge, so dashboards see pool pressure end-to-end."""
        with self._lock:
            return {
                "pool_pages_total": self.n_pages,
                "pool_pages_free": len(self._free),
                # distinct pages some LANE currently holds (parked pages
                # excluded): the leak witness's kv-page gauge — a drained
                # scheduler must read 0 here (analysis/leakcheck.py)
                "pool_pages_in_use": len(
                    {p for blocks in self._lane_blocks for p in blocks}
                ),
                "pool_page_size": self.page_size,
                "pool_parked_sessions": len(self._parked),
                "pool_parked_pages": self._parked_pages,
                "pool_admits": self.admits,
                "pool_prefix_admits": self.prefix_admits,
                "pool_prefix_tokens_shared": self.prefix_tokens_shared,
                "pool_cow_copies": self.cow_copies,
                "pool_parked_evicted": self.parked_evicted,
                "pool_exhausted_sheds": self.exhausted_sheds,
                "pool_parked_total": self.parked_total,
                "pool_resets": self.pool_resets,
                "pool_adopts": self.adopts,
                "pool_adopted_pages_fresh": self.adopted_pages_fresh,
                "pool_swap_in_admits": self.swap_in_admits,
                "pool_host_pages_swapped_in": self.host_pages_swapped_in,
                # staged swap-outs the engine has not drained yet: the
                # host-page leak witness — a drained scheduler must read
                # 0 here (scheduler.leak_counts / analysis/leakcheck.py)
                "pool_swap_pending": len(self._pending_swapouts),
                **self.host_tier.stats(),
            }

    # -- internals (callers hold _lock) --------------------------------------

    def _clear_lane_locked(self, lane: int) -> None:
        self._lane_blocks[lane] = []
        self._lane_reg[lane] = 0
        self._lane_tip[lane] = _ROOT

    def _release_locked(self, lane: int) -> None:
        for p in self._lane_blocks[lane]:
            self._deref_locked(p)
        self._clear_lane_locked(lane)

    def _deref_locked(self, page: int) -> None:
        self._ref[page] -= 1
        if self._ref[page] > 0:
            return
        self._ref[page] = 0
        # remove the tree node this page backs (if any): children whose
        # parent chain just broke become unreachable for NEW matches but
        # stay refcounted by their own holders and remove themselves the
        # same way when their refs drain
        key = self._page_key.pop(page, None)
        if key is not None:
            self._nodes.pop(key, None)
            parent, blk = key
            kids = self._children.get(parent)
            if kids is not None:
                kids.pop(blk, None)
                if not kids:
                    self._children.pop(parent, None)
        self._free.append(page)

    def _evict_entry_locked(self, pid: int, swap: bool = True) -> None:
        blocks = self._parked.pop(pid)
        self._park_index.pop(tuple(blocks), None)
        for p in blocks:
            held = self._park_refs.get(p, 0) - 1
            if held <= 0:
                self._park_refs.pop(p, None)
                self._parked_pages -= 1
            else:
                self._park_refs[p] = held
            # tiered residency: a committed page about to FREE (this
            # deref is its last ref) is staged for swap-out instead of
            # silently dropping to rebuild — the engine drains the
            # staging list (device read -> HostTier.put) before any
            # write that could reuse the page. Captured BEFORE the
            # deref because _deref_locked removes the tree entry.
            if (
                swap
                and self.host_tier.enabled
                and self._ref[p] == 1
                and p in self._page_key
            ):
                node_key = self._page_key[p]
                self._pending_swapouts.append((node_key, node_key[1], p))
            self._deref_locked(p)
        self.parked_evicted += 1

    def _evict_oldest_locked(self) -> None:
        self._evict_entry_locked(next(iter(self._parked)))

    def _evict_parked_locked(self, short_by: int) -> None:
        """Evict parked sessions in LRU order until at least ``short_by``
        more pages are free, SKIPPING sessions that could free nothing —
        every page still pinned by an active lane or the admitting
        request's own shared-ref/COW pins (``ref > park holds`` on all of
        them). Evicting those would destroy a park entry — typically the
        very prefix the admission is sharing — while relieving zero
        pressure, and if the sharing request later failed with
        park=False the hot prefix would vanish from the tree for
        nothing. Eviction frees a session's pages only where its
        refcount drains to zero — blocks shared with an active lane
        stay resident either way. The admit()-side sufficiency check
        guarantees this pass reaches ``short_by`` whenever it runs."""
        before = len(self._free)
        for pid in list(self._parked):
            if len(self._free) - before >= short_by:
                break
            if any(
                self._ref[p] == self._park_refs.get(p, 0)
                for p in self._parked[pid]
            ):
                self._evict_entry_locked(pid)
