"""Multi-step decode (``engine.decode_multi`` + scheduler horizon).

The serving loop's dominant per-token cost is the host round-trip per decode
dispatch (the reference pays the same per-forward socket turnaround,
src/app.cpp:369-402). ``decode_multi`` chains h decode steps in one compiled
``lax.scan`` — the invariant under test is stream identity: multi-step must
emit EXACTLY the tokens single stepping would, for greedy AND device-sampled
lanes, including lanes that stop mid-horizon (their overshoot KV writes must
be unobservable afterwards — the chunked-prefill invariant).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer


@pytest.fixture(scope="module")
def loaded(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    return config, params, tok


def _fresh_engine(config, params, n_lanes=2):
    return InferenceEngine(config, params, n_lanes=n_lanes, prefill_buckets=(4,))


def test_decode_multi_matches_single_steps(loaded):
    """h chained steps emit the exact token sequence of h single steps, for
    a greedy lane and a device-sampled lane together, and leave the engine
    in a state that continues identically."""
    config, params, _ = loaded
    prompt = [5, 9, 3]
    h = 4
    temps = np.asarray([0.0, 0.8], np.float32)
    topps = np.full(2, 0.9, np.float32)
    seeds = np.asarray([0, 123], np.uint32)

    def rollout(engine, n_steps, multi):
        _, g0, pos = engine.prefill(0, prompt)
        _, g1, _ = engine.prefill(1, prompt)
        toks = np.asarray([g0, g1], np.int32)
        out = [toks.copy()]
        positions = np.asarray([pos, pos], np.int32)
        if multi:
            for _ in range(n_steps // h):
                chosen = engine.decode_multi(
                    toks, positions, temps, topps, seeds, h
                )
                for j in range(h):
                    out.append(chosen[j].copy())
                toks = chosen[h - 1].astype(np.int32)
                positions = positions + h
        else:
            for _ in range(n_steps):
                _, greedy, sampled = engine.decode(
                    toks, positions, temps, topps, seeds
                )
                toks = np.where(temps == 0.0, greedy, sampled).astype(np.int32)
                out.append(toks.copy())
                positions = positions + 1
        return np.stack(out)

    single = rollout(_fresh_engine(config, params), 8, multi=False)
    multi = rollout(_fresh_engine(config, params), 8, multi=True)
    np.testing.assert_array_equal(single, multi)
    eng = _fresh_engine(config, params)
    assert eng.stats.multi_dispatches == 0
    eng.decode_multi(np.zeros(2, np.int32), np.zeros(2, np.int32), h=2)
    assert eng.stats.multi_dispatches == 1
    assert eng.stats.decode_steps == 2


def _run_requests(config, params, tok, reqs_spec, multi_step, n_lanes=2):
    engine = _fresh_engine(config, params, n_lanes=n_lanes)
    # pipelined=False isolates the multi-step horizon (the pipelined path
    # would otherwise win the steady-state gate; its own stream-identity
    # tests live in test_pipelined_decode.py)
    sched = ContinuousBatchingScheduler(
        engine, tok, speculative=False, prefix_min_tokens=0,
        multi_step=multi_step, pipelined=False,
    )
    reqs = [
        Request(prompt=p, max_tokens=m, temperature=t, seed=s)
        for (p, m, t, s) in reqs_spec
    ]
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated_tokens) for r in reqs], engine.stats


def test_scheduler_multi_step_stream_identity(loaded):
    """The serving loop with a multi-step horizon produces EXACTLY the
    single-step token streams — greedy and sampled lanes, different
    max_tokens so one lane finishes mid-horizon and its overshoot is
    discarded."""
    config, params, tok = loaded
    spec = [
        ("hello world", 13, 0.0, None),   # greedy, finishes mid-horizon
        ("other prompt", 24, 0.8, 42),    # device-sampled, seeded
    ]
    base, base_stats = _run_requests(config, params, tok, spec, multi_step=0)
    multi, stats = _run_requests(config, params, tok, spec, multi_step=4)
    assert multi == base
    assert stats.multi_dispatches > 0  # the horizon actually engaged
    assert base_stats.multi_dispatches == 0


def test_multi_step_overshoot_does_not_corrupt_prefix_reuse(loaded):
    """A lane that finished mid-horizon holds junk KV past its consumed
    tokens; a later request prefix-reusing that lane must still decode the
    cold-prefill stream (the claimed prefix covers only consumed tokens,
    and junk slots are rewritten before any query reads them)."""
    config, params, tok = loaded
    # > prefix_min_tokens tokens but well under the tiny model's seq_len
    # (an over-long prompt truncates to a max_tokens-dependent TAIL, which
    # destroys the common prefix between the two requests)
    prompt = "shared prefix for reuse "

    def run(prefix_min, multi_step):
        engine = _fresh_engine(config, params, n_lanes=2)
        sched = ContinuousBatchingScheduler(
            engine, tok, speculative=False, prefix_min_tokens=prefix_min,
            multi_step=multi_step, pipelined=False,
        )
        sched.start()
        try:
            a = sched.submit(Request(prompt=prompt, max_tokens=9))
            a.future.result(timeout=300)
            b = sched.submit(Request(prompt=prompt, max_tokens=16))
            b.future.result(timeout=300)
        finally:
            sched.stop()
        assert a.error is None and b.error is None
        return list(b.generated_tokens), engine.stats.prefix_hits

    cold, _ = run(prefix_min=0, multi_step=4)
    warm, hits = run(prefix_min=4, multi_step=4)
    assert hits >= 1  # the second request actually reused lane KV
    assert warm == cold


def test_horizon_gating(loaded):
    """The horizon engages only in steady state: host-exact lanes, queued
    admissions, or a 1-token remainder force single stepping."""
    config, params, tok = loaded
    engine = _fresh_engine(config, params)
    sched = ContinuousBatchingScheduler(
        engine, tok, speculative=False, prefix_min_tokens=0, multi_step=8
    )

    class _L:
        def __init__(self, host_exact, temp, gen, pos, max_tokens):
            class _R:
                temperature = temp
                max_tokens = 0
                generated_tokens = []
            self.request = _R()
            self.request.max_tokens = max_tokens
            self.request.generated_tokens = [0] * gen
            self.host_exact = host_exact
            self.pos = pos

    active = [(0, _L(False, 0.0, 0, 10, 100))]
    assert sched._multi_horizon(active, prefilled=False) == 8
    assert sched._multi_horizon(active, prefilled=True) == 0
    # host-exact sampled lane disables the horizon
    hx = [(0, _L(True, 0.9, 0, 10, 100))]
    assert sched._multi_horizon(hx, prefilled=False) == 0
    # horizon capped by remaining budget, bucketed to powers of two
    short = [(0, _L(False, 0.0, 95, 10, 100))]  # 5 tokens left
    assert sched._multi_horizon(short, prefilled=False) == 4
    one = [(0, _L(False, 0.0, 99, 10, 100))]  # 1 token left
    assert sched._multi_horizon(one, prefilled=False) == 0
    # queued admission disables the horizon
    sched.queue.push(Request(prompt="x"))
    assert sched._multi_horizon(active, prefilled=False) == 0


def test_pod_packet_replays_decode_multi():
    """OP_DECODE_MULTI round-trips the horizon + all operand arrays through
    the control plane packet into the worker's engine.decode_multi."""
    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    calls = []

    class _Eng:
        n_lanes = 2
        SPEC_DRAFT = 3

        class stats:
            @staticmethod
            def reset():
                pass

        def decode_multi(self, tokens, positions, temps, topps, seeds, h,
                         g_states=None):
            calls.append((
                np.asarray(tokens).tolist(), np.asarray(positions).tolist(),
                np.asarray(temps).tolist(), np.asarray(seeds).tolist(), h,
            ))
            return np.zeros((h, 2), np.int32)

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self):
            super().__init__(n_lanes=2, chunk=8)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    plane = _Plane()
    plane.send_decode_multi(
        np.asarray([7, 9], np.int32), np.asarray([3, 4], np.int32),
        np.asarray([0.0, 0.8], np.float32), np.full(2, 0.9, np.float32),
        np.asarray([1, 2], np.uint32), h=4,
    )
    plane.send_stop()

    replay = iter(sent)

    class _ReplayPlane:
        def recv(self):
            return next(replay)

        def slot(self, pkt, i, n):
            return plane.slot(pkt, i, n)

    mh.worker_loop(_Eng(), _ReplayPlane())
    assert calls == [([7, 9], [3, 4], [0.0, pytest.approx(0.8)], [1, 2], 4)]
