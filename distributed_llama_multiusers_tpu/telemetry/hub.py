"""Telemetry hub: the one object the scheduler/server/bench share.

Bundles the span tracer (spans.py), the metrics registry (metrics.py)
with the standard serving instruments pre-registered, and the JSON
logger (logs.py), and exposes the lifecycle hooks the scheduler calls:

    submit -> on_submit          (queued instant, RequestTrace attached)
    admit  -> on_admit           (queued slice, queue-wait histogram)
    chunk  -> on_prefill_chunk   (lane slice, step-duration histogram)
    token  -> on_token           (TTFT on first, inter-token gaps after)
    step   -> on_step / on_pipelined_step  (pipeline-track slices)
    end    -> on_finish / on_unadmitted / on_error  (summary, counters,
              one JSON log line, finish instant)

Design constraint, inherited from the async pipeline: NO hook runs
inside the pipelined dispatch half. Dispatch→consume step slices are
recorded by ``on_pipelined_step`` from the scheduler's consume half, one
step behind, where the host is already blocking on the lagged readback —
dlint's ``pipeline-sync`` check stays green because the dispatch half
never calls in here.

Exposition: ``render_prometheus(bridge=stats_dict)`` re-publishes the
``/stats`` payload as ``dllama_stats_*`` gauges next to the native
histograms/counters, sampled from the SAME snapshot the JSON endpoint
serves — so ``/metrics`` and ``/stats`` reconcile by construction.
"""

from __future__ import annotations

import time

from .logs import JsonLogger, default_logger
from .metrics import LATENCY_BUCKETS_S, MetricsRegistry
from .spans import RequestTrace, SpanTracer
from .trace import dump_chrome_trace, tracer_chrome_trace
from .tracectx import trace_id_of

STATS_PREFIX = "dllama_stats_"


class Telemetry:
    def __init__(
        self,
        tracer: SpanTracer | None = None,
        registry: MetricsRegistry | None = None,
        logger: JsonLogger | None = None,
        trace_capacity: int = 16384,
        replica: str | None = None,
    ):
        self.tracer = tracer or SpanTracer(capacity=trace_capacity)
        self.registry = registry or MetricsRegistry()
        self.logger = logger or default_logger()
        # replica attribution on every span (ISSUE 20): the merged
        # cross-replica timeline needs each event to say where it ran.
        # Set at construction or later by the server once it knows its id
        # (ApiServer stamps it when the scheduler built its own hub).
        self.replica = replica
        reg = self.registry
        self.ttft = reg.histogram(
            "dllama_ttft_seconds",
            "submit -> first consumed token, per request",
            LATENCY_BUCKETS_S,
        )
        self.tbt = reg.histogram(
            "dllama_time_between_tokens_seconds",
            "gap between consecutive consumed tokens, per lane",
            LATENCY_BUCKETS_S,
        )
        self.queue_wait = reg.histogram(
            "dllama_queue_wait_seconds",
            "submit -> queue pop, per popped request (pops that resolve "
            "cancelled/expired without claiming a lane included)",
            LATENCY_BUCKETS_S,
        )
        self.step_duration = reg.histogram(
            "dllama_step_duration_seconds",
            "one engine dispatch: prefill chunk, decode step (sync/spec/"
            "multi horizon), or pipelined dispatch->lagged-consume span",
            LATENCY_BUCKETS_S,
        )
        self.requests_finished = reg.counter(
            "dllama_requests_finished_total",
            "finished requests by finish_reason (shed = drain-flushed, "
            "error = failed before generating)",
        )
        self.tokens_generated = reg.counter(
            "dllama_tokens_generated_total", "tokens consumed across lanes"
        )
        self.overlap_fraction = reg.gauge(
            "dllama_overlap_fraction",
            "overlap_s / (overlap_s + decode_s): fraction of engine decode "
            "wall-time the async pipeline hid behind device execution",
        )
        # pod-serving sync cost next to TTFT/TBT: the estimated collective
        # payload accrued per decode-family dispatch (reconciles with the
        # /stats sync_bytes_total field the bridge republishes — same
        # source, delta-fed below) and the MEASURED per-step collective
        # time from profiler probes (engine.measured_sync_stats)
        self.sync_bytes = reg.counter(
            "dllama_sync_bytes_total",
            "estimated collective payload bytes (per chip) dispatched with "
            "decode-family steps, from the compiled program's post-SPMD HLO",
        )
        self.sync_seconds = reg.histogram(
            "dllama_sync_seconds",
            "measured per-decode-step collective time (profiler probe: "
            "engine.measured_sync_stats)",
            LATENCY_BUCKETS_S,
        )
        # failure containment (serving/breaker.py, runtime/scheduler.py):
        # the breaker state machine as a gauge and classified failures as
        # a labelled counter — both reconciled with the /stats twins via
        # bridge_stats (the state gauge is set from breaker_state_code,
        # the counter delta-fed from the engine_failures dict, so counter
        # semantics survive window resets like dllama_sync_bytes_total)
        self.breaker_state = reg.gauge(
            "dllama_breaker_state",
            "serving circuit breaker: 0 closed, 1 half-open, 2 open "
            "(anything > 0 means /health is reporting unhealthy)",
        )
        self.engine_failures = reg.counter(
            "dllama_engine_failures_total",
            "classified serving failures by failure_class label: engine "
            "(dispatch/consume/transfer raise, contained), request "
            "(per-request input error), watchdog (stalled step)",
        )
        # zero-flush serving: speculation acceptance as a native counter
        # next to the dllama_stats_spec_* gauges the bridge republishes —
        # delta-fed from the /stats spec_emitted field (same recipe as
        # dllama_sync_bytes_total) so counter semantics survive
        # engine.stats.reset() windows
        self.spec_accepted = reg.counter(
            "dllama_spec_accepted_total",
            "tokens consumed from speculative verify steps on DRAFTED "
            "lanes (the /stats spec_emitted field, delta-fed)",
        )
        # crash-durable serving (serving/journal.py, serving/recovery.py):
        # journal writes and replay re-admissions as native counters next
        # to the dllama_stats_* gauges the bridge republishes — delta-fed
        # from the same /stats fields, so the endpoints reconcile while
        # the counters keep Prometheus semantics across window resets
        self.journal_records = reg.counter(
            "dllama_journal_records_total",
            "request-journal records durably written (the /stats "
            "journal_records field, delta-fed)",
        )
        self.recovered_requests = reg.counter(
            "dllama_recovered_requests_total",
            "crashed requests re-admitted by journal replay (the /stats "
            "recovered_requests field, delta-fed)",
        )
        # compile stability (analysis/jitcheck.py): post-warmup XLA
        # compiles as a native counter next to the
        # dllama_stats_jit_compiles_after_warmup gauge the bridge
        # republishes — delta-fed with the sync-bytes recipe so alerting
        # on `increase(dllama_jit_compiles_total[5m]) > 0` works even
        # across /stats window semantics; MUST stay flat in steady
        # serving (one compiled program per family/bucket, warmup-only)
        self.jit_compiles = reg.counter(
            "dllama_jit_compiles_total",
            "XLA backend compiles observed after warmup_engine armed the "
            "recompile witness (the /stats jit_compiles_after_warmup "
            "field, delta-fed) — non-zero means a mid-serving recompile",
        )
        # resource lifecycle (analysis/leakcheck.py): resources found
        # still held at a drain point (scheduler stop, registry close) as
        # a native counter beside the dllama_stats_resource_leaks_total
        # gauge the bridge republishes — delta-fed with the sync-bytes
        # recipe; MUST stay flat (a rise means an acquire escaped every
        # release path, the runtime twin of the resource-balance lint)
        self.resource_leaks = reg.counter(
            "dllama_resource_leaks_total",
            "resources still held at a drain point — scheduler stop or "
            "stream-registry close (the /stats resource_leaks_total "
            "field, delta-fed); non-zero means a lifecycle leak",
        )
        # tiered KV residency (runtime/kvpool.py HostTier): page traffic
        # across the HBM<->host-RAM boundary as a native direction-labelled
        # counter beside the dllama_stats_pool_host_* / dllama_stats_swap_*
        # gauges the bridge republishes — delta-fed from the /stats
        # swap_ins / swap_outs fields with the sync-bytes recipe (a drop
        # means the engine's swap counters were reset: re-baseline, the
        # counter never goes back)
        self.kv_swap = reg.counter(
            "dllama_kv_swap_total",
            "KV pages moved across the residency boundary by direction "
            "label: 'in' host-RAM->HBM reactivations, 'out' HBM->host-RAM "
            "swap-outs (the /stats swap_ins / swap_outs fields, delta-fed)",
        )
        self._sync_bytes_seen = 0
        self._jit_compiles_seen = 0.0
        self._resource_leaks_seen = 0.0
        self._spec_emitted_seen = 0.0
        self._journal_records_seen = 0.0
        self._recovered_seen = 0.0
        self._failures_seen: dict[str, float] = {}
        self._kv_swap_seen: dict[str, float] = {"in": 0.0, "out": 0.0}

    # -- queue binding -------------------------------------------------------

    def bind_queue(self, queue) -> bool:
        """Feed the queue-wait histogram from the queue's own pop-time
        measurement when it offers one (QosQueue.set_wait_observer), so
        the histogram's count reconciles with ``queue_popped`` exactly.
        Returns False when the queue can't (bare FIFO) — the scheduler
        then observes at claim time instead."""
        setter = getattr(queue, "set_wait_observer", None)
        if setter is None:
            return False
        setter(self.queue_wait.observe)
        return True

    # -- request lifecycle hooks --------------------------------------------

    @staticmethod
    def trace_of(req) -> RequestTrace:
        tel = getattr(req, "tel", None)
        if tel is None:
            tel = req.tel = RequestTrace(getattr(req, "submitted_at", None))
        return tel

    def span_args(self, req=None, extra: dict | None = None) -> dict | None:
        """The args every span carries since ISSUE 20: the request's
        fleet-wide ``trace_id`` (when it carried an ``X-DLlama-Trace``
        context) and this process's ``replica`` id — what the router's
        cross-replica merge filters and attributes by."""
        args = dict(extra) if extra else {}
        if req is not None:
            tid = trace_id_of(getattr(req, "trace", None))
            if tid:
                args["trace_id"] = tid
        if self.replica:
            args["replica"] = self.replica
        return args or None

    def on_submit(self, req) -> None:
        tel = self.trace_of(req)
        self.tracer.instant("submitted", "queue", ts=tel.span_t0,
                            req_id=req.id, args=self.span_args(req))

    def on_admit(self, req, lane: int) -> None:
        tel = self.trace_of(req)
        tel.admitted_at = req.admitted_at
        tel.lane = lane
        now_pc = self.tracer.now()
        self.tracer.slice("queued", "queue", tel.span_t0, now_pc,
                          req_id=req.id,
                          args=self.span_args(req, {"lane": lane}))
        tel.span_t0 = now_pc  # the generate slice starts here

    def on_queue_pop(self, req, now: float) -> None:
        """Fallback queue-wait observation for queues WITHOUT a pop-time
        observer (bare FIFO): called by the scheduler right after every
        pop — cancelled/expired pops included — so both queue kinds feed
        the histogram the same population."""
        t0 = getattr(req, "submitted_at", None)
        if t0 is not None:
            self.queue_wait.observe(max(0.0, now - t0))

    def on_prefix_hit(self, req, tokens_saved: int) -> None:
        self.trace_of(req).prefix_saved = int(tokens_saved)

    def on_fused_admit(self, req) -> None:
        """The request's prompt chunks are riding fused dispatches inside
        the live chain (claimed in-chain, or joined the chain with chunks
        still pending)."""
        self.trace_of(req).fused_admitted = True

    def on_prefill_chunk(self, req, lane: int, t0: float, n_tokens: int,
                         fused: bool = False) -> None:
        now_pc = self.tracer.now()
        self.tracer.slice(
            "prefill.fused" if fused else "prefill.sync", f"lane{lane}",
            t0, now_pc, req_id=req.id,
            args=self.span_args(req, {"tokens": n_tokens}),
        )
        if not fused:
            # fused chunks ride a pipelined dispatch that on_pipelined_step
            # already times; observing both would double-count the span
            self.step_duration.observe(max(0.0, now_pc - t0))

    def on_token(self, req, now: float | None = None) -> None:
        """One consumed token (``now`` = time.monotonic()). First token
        observes TTFT; every later one observes the inter-token gap."""
        tel = self.trace_of(req)
        if now is None:
            now = time.monotonic()
        first = tel.first_token_at is None
        tel.on_token(now)
        self.tokens_generated.inc()
        if first:
            if tel.ttft_s is not None:
                self.ttft.observe(tel.ttft_s)
        else:
            self.tbt.observe(tel.gaps[-1])

    # -- step hooks ----------------------------------------------------------

    def on_step(self, kind: str, t0: float, args: dict | None = None) -> None:
        """One synchronous engine dispatch (kind: sync/spec/multi)."""
        now_pc = self.tracer.now()
        self.tracer.slice(f"step.{kind}", "pipeline", t0, now_pc,
                          args=self.span_args(extra=args))
        self.step_duration.observe(max(0.0, now_pc - t0))

    def on_pipelined_step(self, t_dispatch: float, fused_info=None,
                          kind: str = "pipelined") -> None:
        """One pipelined step, recorded at CONSUME time (one step behind):
        the slice spans dispatch -> lagged readback completion. ``kind``
        distinguishes the in-chain spec verify steps
        (``"spec_pipelined"`` — the zero-flush speculation path) from
        plain pipelined decodes on the trace. For a fused prefill+decode
        step, ``fused_info`` is the scheduler's
        ``(lane_idx, lane, final, n_chunk)`` and the admitting lane also
        gets a ``prefill.fused`` slice on its own track."""
        now_pc = self.tracer.now()
        if fused_info is None:
            self.tracer.slice(f"step.{kind}", "pipeline", t_dispatch,
                              now_pc, args=self.span_args())
        else:
            lane_idx, lane, final, n_chunk = fused_info
            req = lane.request
            req_id = getattr(req, "id", None)
            # a verify step that ALSO carries a chunk keeps its spec
            # identity on the trace — the composition the zero-flush
            # chain exists for must be countable, not folded into plain
            # fused slices
            name = "step.fused" if kind == "pipelined" else "step.spec_fused"
            self.tracer.slice(
                name, "pipeline", t_dispatch, now_pc, req_id=req_id,
                args=self.span_args(req, {"chunk": n_chunk, "final": final}),
            )
            if req is not None:
                self.on_prefill_chunk(req, lane_idx, t_dispatch, n_chunk,
                                      fused=True)
        self.step_duration.observe(max(0.0, now_pc - t_dispatch))

    def observe_sync_probe(self, breakdown: dict, steps: int = 1) -> None:
        """Feed a measured per-step sync split (the dict from
        ``engine.measured_sync_stats`` / ``measured_step_breakdown``) into
        the ``dllama_sync_seconds`` histogram — one observation per
        measured step, so the histogram count reads as probed steps. No-op
        when the probe had no collective data (off-mesh, wall-only)."""
        ms = breakdown.get("sync_ms")
        if ms is None:
            return
        for _ in range(max(1, int(steps))):
            self.sync_seconds.observe(ms / 1e3)

    def on_flush(self, live: int, admitting: int) -> None:
        self.tracer.instant(
            "pipeline.flush", "pipeline",
            args=self.span_args(extra={"live": live, "admitting": admitting}),
        )

    # -- failure containment -------------------------------------------------

    def on_engine_failure(self, error: str, lanes_failed: int,
                          breaker_state: str) -> None:
        """One engine-scoped containment round (runtime/scheduler.py's
        supervised loop): the loop caught an engine raise, failed the
        affected lanes, and kept serving. One trace instant + one
        structured log line — the event operators grep for when error-rate
        alarms fire."""
        self.tracer.instant(
            "engine.failure", "pipeline",
            args=self.span_args(extra={
                "error": error[:200],
                "lanes_failed": lanes_failed,
                "breaker_state": breaker_state,
            }),
        )
        self.logger.emit(
            "engine_failure",
            error=error[:200],
            lanes_failed=lanes_failed,
            breaker_state=breaker_state,
        )

    def on_watchdog_trip(self, waited_s: float, fatal: bool) -> None:
        """The step watchdog (serving/watchdog.py) found a dispatched step
        with no progress past its deadline. The watchdog emits its own
        log line before any fatal exit; this is the scheduler-side trace
        instant tying the trip to the pipeline track."""
        self.tracer.instant(
            "watchdog.trip", "pipeline",
            args=self.span_args(
                extra={"waited_s": round(waited_s, 3), "fatal": fatal}
            ),
        )

    # -- request endings -----------------------------------------------------

    def _summarize(self, req, reason: str | None,
                   error: str | None = None) -> dict:
        tel = self.trace_of(req)
        summary = tel.summary(req, reason)
        if error is not None:
            summary["error"] = error
        req.summary = summary
        self.logger.emit("request", **summary)
        return summary

    def on_finish(self, req, lane: int, reason: str | None) -> None:
        """A request that held a lane ended (stop/length/cancel/timeout)."""
        tel = self.trace_of(req)
        track = f"lane{lane}"
        self.tracer.slice("generate", track, tel.span_t0, req_id=req.id,
                          args=self.span_args(req,
                                              {"finish_reason": reason}))
        self.tracer.instant(f"finish.{reason}", track, req_id=req.id,
                            args=self.span_args(req))
        self.requests_finished.inc(finish_reason=str(reason))
        self._summarize(req, reason)

    def on_unadmitted(self, req, reason: str) -> None:
        """A request resolved without ever claiming a lane (queue timeout,
        cancel while queued, drain shed)."""
        tel = self.trace_of(req)
        self.tracer.slice("queued", "queue", tel.span_t0, req_id=req.id,
                          args=self.span_args(req,
                                              {"finish_reason": reason}))
        self.tracer.instant(f"finish.{reason}", "queue", req_id=req.id,
                            args=self.span_args(req))
        self.requests_finished.inc(finish_reason=reason)
        self._summarize(req, reason)

    def on_error(self, req, lane: int | None, error: str) -> None:
        """A request failed before generating (tokenization/engine error).
        The error string rides the summary BEFORE the log line is emitted,
        so the request's log record carries the reason the 500 names."""
        track = "queue" if lane is None else f"lane{lane}"
        self.tracer.instant("finish.error", track, req_id=req.id,
                            args=self.span_args(req,
                                                {"error": error[:200]}))
        self.requests_finished.inc(finish_reason="error")
        self._summarize(req, "error", error=error[:200])

    # -- startup -------------------------------------------------------------

    def startup_log(self, event: str, **fields) -> None:
        """One structured line deployments verify config from (satellite:
        mesh shape / buckets / pipeline depth / fused on-off in logs)."""
        self.logger.emit(event, **fields)

    # -- exposition ----------------------------------------------------------

    def bridge_stats(self, stats: dict) -> None:
        """Republish a ``/stats`` payload as ``dllama_stats_*`` gauges
        (dict-valued histogram counters become labelled gauges), plus the
        derived overlap-fraction gauge. Values land verbatim, so a scrape
        reconciles with the JSON endpoint field-for-field."""
        reg = self.registry
        for key, value in stats.items():
            if value is None:
                continue
            name = STATS_PREFIX + key
            if isinstance(value, bool):
                reg.gauge(name).set(1.0 if value else 0.0)
            elif isinstance(value, (int, float)):
                reg.gauge(name).set(float(value))
            elif isinstance(value, dict):
                g = reg.gauge(name)
                for k, v in value.items():
                    if isinstance(v, (int, float)):
                        g.set(float(v), key=str(k))
        overlap = float(stats.get("overlap_s") or 0.0)
        decode = float(stats.get("decode_s") or 0.0)
        if overlap + decode > 0:
            self.overlap_fraction.set(overlap / (overlap + decode))
        # the native sync-bytes counter tracks the same accounting the
        # dllama_stats_sync_bytes_total gauge republishes, delta-fed so it
        # keeps Prometheus counter semantics across engine.stats.reset()
        # windows (the gauge resets with /stats; the counter never goes back)
        total = stats.get("sync_bytes_total")
        if isinstance(total, (int, float)):
            if total > self._sync_bytes_seen:
                self.sync_bytes.inc(float(total - self._sync_bytes_seen))
            # a drop means the stats window reset: re-baseline, counter keeps
            self._sync_bytes_seen = float(total)
        # speculation acceptance: delta-fed like the sync-bytes counter,
        # with one extra rule — spec_emitted can DIP without a window
        # reset (SpecStream.discard_pending retracts a partially consumed
        # verify step), and re-baselining downward would re-count the
        # retracted tokens on the next rise. Keep the HIGH-WATER mark
        # across a partial dip (the counter stays monotone; the retracted
        # tokens remain counted — they really were consumed) and
        # re-baseline only on a drop to 0 (engine.stats.reset()).
        emitted = stats.get("spec_emitted")
        if isinstance(emitted, (int, float)) and not isinstance(emitted, bool):
            if emitted > self._spec_emitted_seen:
                self.spec_accepted.inc(float(emitted - self._spec_emitted_seen))
                self._spec_emitted_seen = float(emitted)
            elif emitted == 0:
                self._spec_emitted_seen = 0.0
        # crash durability: journal writes and recovery re-admissions,
        # delta-fed with the sync-bytes recipe (monotone within a
        # process; a drop to 0 means the journal/coordinator was swapped,
        # re-baseline without re-counting)
        for fld, ctr, seen_attr in (
            ("journal_records", self.journal_records,
             "_journal_records_seen"),
            ("recovered_requests", self.recovered_requests,
             "_recovered_seen"),
            # jit_compiles_after_warmup never resets within a process
            # (engine.stats.reset() deliberately keeps it), so the
            # monotone delta-feed recipe applies verbatim
            ("jit_compiles_after_warmup", self.jit_compiles,
             "_jit_compiles_seen"),
            # resource_leaks_total never resets within a process either
            # (leakcheck.force(fresh=True) is test-only), so the same
            # monotone recipe applies
            ("resource_leaks_total", self.resource_leaks,
             "_resource_leaks_seen"),
        ):
            v = stats.get(fld)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                seen = getattr(self, seen_attr)
                if v > seen:
                    ctr.inc(float(v - seen))
                setattr(self, seen_attr, float(v))
        # tiered KV residency: direction-labelled swap-page counter,
        # delta-fed from the engine's swap traffic counters (monotone
        # while the engine lives; a drop means reset_swap_stats() /
        # warmup re-baselined — re-baseline here too, counter keeps)
        for fld, direction in (("swap_ins", "in"), ("swap_outs", "out")):
            v = stats.get(fld)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                seen = self._kv_swap_seen[direction]
                if v > seen:
                    self.kv_swap.inc(float(v - seen), direction=direction)
                self._kv_swap_seen[direction] = float(v)
        # breaker exposition (serving/breaker.py): the state gauge tracks
        # breaker_state_code verbatim; the classified-failure counter is
        # delta-fed from the engine_failures dict, same recipe as above
        code = stats.get("breaker_state_code")
        if isinstance(code, (int, float)) and not isinstance(code, bool):
            self.breaker_state.set(float(code))
        fails = stats.get("engine_failures")
        if isinstance(fails, dict):
            for cls, v in fails.items():
                if not isinstance(v, (int, float)):
                    continue
                seen = self._failures_seen.get(cls, 0.0)
                if v > seen:
                    self.engine_failures.inc(
                        float(v - seen), failure_class=str(cls)
                    )
                self._failures_seen[cls] = float(v)

    def render_prometheus(self, bridge: dict | None = None) -> str:
        if bridge:
            self.bridge_stats(bridge)
        return self.registry.render()

    def chrome_trace(self, since: int = 0,
                     trace_id: str | None = None) -> dict:
        return tracer_chrome_trace(self.tracer, since=since,
                                   trace_id=trace_id)

    def dump_trace(self, path: str) -> dict:
        doc = dump_chrome_trace(self.tracer, path)
        self.logger.emit("trace_dump", path=path,
                         events=len(doc["traceEvents"]))
        return doc
