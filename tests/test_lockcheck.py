"""Runtime lock-order witness (DLLAMA_LOCKCHECK=1, lockcheck.py).

Three layers, mirroring the test_dlint.py contract:

- **witness unit tests** — the wrapper records per-thread chains,
  non-blocking probes stay silent, Condition integration keeps the
  chain honest;
- **seeded inversion fixtures** — the witness actually FIRES: on a
  runtime-observed order inverted later, on a statically declared order
  inverted at first touch, and on re-entry of a non-reentrant lock;
- **the tier-1 gate** — the real QoS + telemetry paths run CLEAN under
  the witness, in-process (fresh witness, static seed included) and as
  a subprocess rerun of their suites with DLLAMA_LOCKCHECK=1 in the
  environment (so every lock those suites construct is wrapped).

Pure stdlib apart from the subprocess rerun.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from distributed_llama_multiusers_tpu import lockcheck
from distributed_llama_multiusers_tpu.lockcheck import (
    LockOrderViolation,
    WitnessLock,
    make_lock,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture
def witness_on():
    """Force the witness on (fresh order graph, static seed applied on
    first use) and restore the env-driven default afterwards."""
    lockcheck.force(True, fresh=True)
    try:
        yield lockcheck.witness()
    finally:
        lockcheck.force(None, fresh=True)


# -- wiring -------------------------------------------------------------------


def test_disabled_returns_plain_lock():
    lockcheck.force(False, fresh=True)
    try:
        lk = make_lock("QosQueue._lock")
        assert not isinstance(lk, WitnessLock)
        assert isinstance(lk, type(threading.Lock()))
    finally:
        lockcheck.force(None, fresh=True)


def test_enabled_wraps_and_tracks_chain(witness_on):
    a = make_lock("Fix.a")
    b = make_lock("Fix.b")
    assert isinstance(a, WitnessLock)
    with a:
        with b:
            assert witness_on.held() == ("Fix.a", "Fix.b")
    assert witness_on.held() == ()


def test_nonblocking_probe_does_not_fire(witness_on):
    """Condition._is_owned probes held locks with acquire(False) — the
    witness must not mistake the probe for a deadlocking re-entry."""
    a = make_lock("Fix.a")
    with a:
        assert a.acquire(False) is False
    assert a.acquire(False) is True
    a.release()
    assert witness_on.held() == ()


def test_timeout_acquire_pops_chain(witness_on):
    a = make_lock("Fix.a")
    a.acquire()
    done = []

    def contender():
        got = a.acquire(timeout=0.05)
        done.append(got)

    t = threading.Thread(target=contender)
    t.start()
    t.join()
    assert done == [False]
    a.release()
    assert witness_on.held() == ()


# -- the seeded inversion fixtures: the witness FIRES -------------------------


def test_runtime_inversion_fires(witness_on):
    """The acceptance-criterion fixture: establish A->B at runtime, then
    acquire B->A — the witness raises at the inverted acquire instead of
    letting the schedule decide whether the pod hangs today."""
    a = make_lock("Fix.a")
    b = make_lock("Fix.b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderViolation, match="inversion"):
            with a:
                pass
    assert witness_on.held() == ()


def test_static_seeded_order_fires_without_prior_observation(witness_on):
    """An order only the STATIC graph knows (seeded, never observed in
    this process) still fires on the first inverted acquire."""
    witness_on.add_order("Decl.x", "Decl.y", site="static fixture:1")
    x = make_lock("Decl.x")
    y = make_lock("Decl.y")
    with y:
        with pytest.raises(LockOrderViolation, match="static fixture:1"):
            with x:
                pass


def test_reentry_fires(witness_on):
    a = make_lock("Fix.a")
    with a:
        with pytest.raises(LockOrderViolation, match="re-acquisition"):
            a.acquire()


def test_transitive_inversion_fires(witness_on):
    """A->B and B->C established; acquiring A under C inverts through the
    transitive closure, not just direct edges."""
    a, b, c = (make_lock(f"Fix.{n}") for n in "abc")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(LockOrderViolation):
            with a:
                pass


def test_violation_is_assertion_error():
    """Test harnesses treat the witness verdict as a failed invariant."""
    assert issubclass(LockOrderViolation, AssertionError)


# -- the static seed matches the shipped declarations -------------------------


def test_static_seed_vocabulary_matches_declarations(witness_on):
    """The witness names (make_lock literals) and the static model's
    class-qualified ids are one vocabulary — if a declaration is renamed
    without its literal, dlint's lock-order check fails; if a make_lock
    site disappears, this rot-guard does."""
    import ast

    from distributed_llama_multiusers_tpu.analysis.lockgraph import scan_paths

    pkg = REPO_ROOT / "distributed_llama_multiusers_tpu"
    model = scan_paths([pkg])
    model.ensure_semantics()
    literals = set()
    for py in pkg.rglob("*.py"):
        for node in ast.walk(ast.parse(py.read_text())):
            if (
                isinstance(node, ast.Call)
                and getattr(node.func, "attr", getattr(node.func, "id", None))
                == "make_lock"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                literals.add(node.args[0].value)
    assert {
        "QosQueue._lock", "EngineStats.lock", "SpanTracer._trace_lock",
        "JsonLogger._log_lock", "Counter._m_lock", "Gauge._m_lock",
        "Histogram._m_lock", "MetricsRegistry._reg_lock", "native._lock",
    } <= literals
    for name in literals:
        assert name in model.decls, (
            f"witness name {name!r} has no static declaration"
        )


# -- Condition integration (the QosQueue shape) -------------------------------


def test_condition_over_witnessed_lock(witness_on):
    lk = make_lock("Cond.q")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            while not hits:
                if not cv.wait(timeout=1.0):
                    return
        hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.05)
    with cv:
        hits.append("go")
        cv.notify()
    t.join(timeout=2)
    assert not t.is_alive()
    assert hits == ["go", "woke"]
    assert witness_on.held() == ()


# -- tier-1: the real QoS/telemetry paths run clean under the witness ---------


def test_real_qos_and_telemetry_paths_clean(witness_on):
    """Drive the real QosQueue (witnessed lock + condition), the wait
    observer wired to the real Histogram (witnessed _m_lock), the span
    tracer, the JSON logger, and EngineStats — concurrently — with the
    static seed active. Any nesting that contradicts the computed order
    raises out of a worker and fails the test."""
    from distributed_llama_multiusers_tpu.runtime.engine import EngineStats
    from distributed_llama_multiusers_tpu.serving.qos import QosQueue
    from distributed_llama_multiusers_tpu.telemetry.hub import Telemetry

    tel = Telemetry(trace_capacity=256)
    q = QosQueue(capacity=256, quantum=32.0)
    assert isinstance(q._lock, WitnessLock)
    assert tel.bind_queue(q) is True  # observer runs outside the queue lock
    stats = EngineStats()
    assert isinstance(stats.lock, WitnessLock)

    errors: list[BaseException] = []

    class Req:
        def __init__(self, i):
            self.user_id = f"u{i % 3}"
            self.priority = 1
            self.max_tokens = 8
            self.submitted_at = None

    def producer(i):
        try:
            for _ in range(50):
                q.push(Req(i))
                tel.tracer.instant("submitted", "queue")
                tel.logger.emit("test", i=i)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    def consumer():
        try:
            for _ in range(100):
                req = q.pop(timeout=1.0)
                if req is None:
                    return
                with stats.lock:
                    stats.decode_steps += 1
                q.stats()
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=producer, args=(i,)) for i in range(2)]
    threads += [threading.Thread(target=consumer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    q.drain()
    assert not errors, errors
    assert tel.queue_wait.count > 0  # the observer really ran
    assert witness_on.held() == ()


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_qos_and_telemetry_suites_clean_under_lockcheck():
    """The tier-1 fixture the issue asks for: rerun the QoS + telemetry
    suites in a subprocess with DLLAMA_LOCKCHECK=1, so EVERY lock they
    construct is witness-wrapped (static seed included). A lock-order
    regression on those paths fails this test even when the interleaving
    never actually deadlocks."""
    env = dict(os.environ)
    env["DLLAMA_LOCKCHECK"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            "tests/test_qos.py", "tests/test_telemetry.py",
            "-q", "-p", "no:cacheprovider",
        ],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (
        f"QoS/telemetry suites failed under DLLAMA_LOCKCHECK=1:\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    )
