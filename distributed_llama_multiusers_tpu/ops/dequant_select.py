"""Per-site dequant mode selection for ``DLLAMA_DEQUANT=auto``.

The dequant arithmetic variant (ops/pallas_q40.DEQUANT_MODES) is a static
argument of the jitted Q40 matmul: switching it retraces every family that
touched it. So "auto" cannot mean "measure and switch live" — it means
resolve each matmul site's mode ONCE, deterministically, from a small
persisted selection table keyed by (d_in, d_out, m-class), before
``warmup_engine`` compiles the step families. The table is checked in
(ops/dequant_table.json), seeded from PERF.md round-5 hardware evidence,
and refreshed out-of-band by the measurement loops (bench.py's in-bench
micro-A/B, scripts/kernel_sweep.py --update-table via evidence_loop.sh,
scripts/kernel_lab3.py --adopt) through ``record_win``.

Everything in this module is HOST state: rules are plain python dicts and
strings. No device arrays may ever be constructed into the table or the
resolution caches — this file is registered in the dlint jit-stability
scope (analysis/jit_surface_check.py) exactly like runtime/engine.py.

m-class: "decode" is m <= BLOCKDOT_MAX_M (the blockdot family's own cap),
"prefill" is everything wider. Resolution happens inside
``q40_matmul_pallas`` at trace time only, so a warmed family never
re-resolves; ``freeze_for_serving`` (called by warmup_engine) additionally
pins the loaded table so a mid-serving reload cannot change answers.
"""

from __future__ import annotations

import json
import os
import threading
import time

_TABLE_ENV = "DLLAMA_DEQUANT_TABLE"
_DEFAULT_TABLE = os.path.join(os.path.dirname(__file__), "dequant_table.json")

M_CLASSES = ("decode", "prefill")

# Conservative default when no table rule matches at all (the shipped table
# always matches via wildcards): the bf16 chain every mode falls back to.
FALLBACK_MODE = "bf16chain"


def m_class_of(m: int) -> str:
    from .pallas_q40 import BLOCKDOT_MAX_M

    return "decode" if m <= BLOCKDOT_MAX_M else "prefill"


class DequantTable:
    """The persisted (d_in, d_out, m-class) -> mode selection table.

    Rules match exact values or "*" wildcards; the most specific matching
    rule wins (each exact field scores one, ties keep the earlier row).
    Loading validates every rule against the known kernel-mode list and
    fails loudly — a stale or hand-edited table must never silently route
    a site to the wrong chain. PURE host state: ``rules`` holds the parsed
    JSON dicts as-is."""

    def __init__(self, path: str | None = None):
        from .pallas_q40 import DEQUANT_MODES

        self.path = path or os.environ.get(_TABLE_ENV) or _DEFAULT_TABLE
        with open(self.path) as f:
            data = json.load(f)
        rules = data.get("rules", [])
        for r in rules:
            if r.get("mode") not in DEQUANT_MODES:
                raise ValueError(
                    f"{self.path}: rule {r!r} has unknown mode "
                    f"{r.get('mode')!r}; one of {DEQUANT_MODES}"
                )
            if r.get("m_class", "*") not in M_CLASSES + ("*",):
                raise ValueError(
                    f"{self.path}: rule {r!r} has unknown m_class "
                    f"{r.get('m_class')!r}; one of {M_CLASSES + ('*',)}"
                )
        self.rules = rules
        self.provenance = {
            "path": self.path,
            "version": data.get("version"),
            "updated": data.get("updated"),
            "rows": len(rules),
            "provenance": data.get("provenance"),
        }

    def resolve(self, d_in: int, d_out: int, m_class: str) -> str:
        best, best_score = None, -1
        for r in self.rules:
            score = 0
            for key, val in (("d_in", d_in), ("d_out", d_out),
                             ("m_class", m_class)):
                rv = r.get(key, "*")
                if rv == "*":
                    continue
                if rv != val:
                    score = -1
                    break
                score += 1
            if score > best_score:
                best, best_score = r, score
        if best is None:
            return FALLBACK_MODE
        return best["mode"]


_lock = threading.Lock()
_table: DequantTable | None = None
_frozen = False
_sites: dict[str, str] = {}  # "d_inxd_out/m_class" -> resolved mode


def _get_table() -> DequantTable:
    global _table
    with _lock:
        if _table is None:
            _table = DequantTable()
        return _table


def resolve_mode(d_in: int, d_out: int, m: int) -> str:
    """The auto-mode hook q40_matmul_pallas calls at trace time: the
    table's answer for this site, recorded into the site map surfaced on
    /stats and stamped into bench artifacts."""
    cls = m_class_of(m)
    mode = _get_table().resolve(d_in, d_out, cls)
    with _lock:
        _sites[f"{d_in}x{d_out}/{cls}"] = mode
    return mode


def resolved_sites() -> dict[str, str]:
    """Copy of the per-site resolution map (empty unless auto resolved
    something — fixed modes never consult the table)."""
    with _lock:
        return dict(_sites)


def freeze_for_serving() -> dict | None:
    """Load + pin the selection table before warmup compiles anything.
    After this, ``reload_table`` refuses: the mode is a static argname, so
    a live table change would retrace every warmed family mid-serving.
    Returns the table provenance under auto, None for fixed modes (the
    table is not even loaded then)."""
    from . import pallas_q40 as pq

    global _frozen
    prov = dict(_get_table().provenance) if pq.DEQUANT_MODE == "auto" else None
    with _lock:
        _frozen = True
    return prov


def reload_table(path: str | None = None) -> DequantTable:
    """Swap in a (possibly different) table file — measurement tooling and
    tests only. Refuses once frozen for serving."""
    global _table
    with _lock:
        if _frozen:
            raise RuntimeError(
                "dequant selection table is frozen after warmup — the mode "
                "is a static argname, a live switch recompiles every warmed "
                "family; restart to pick up table changes"
            )
        _table = DequantTable(path)
        _sites.clear()
        return _table


def _reset_for_tests() -> None:
    global _table, _frozen
    with _lock:
        _table = None
        _frozen = False
        _sites.clear()


def dequant_stats() -> dict:
    """The dequant attribution payload for /stats and bench artifacts:
    the configured mode knob, the per-site resolutions (auto), and the
    selection-table provenance when a table is loaded."""
    from . import pallas_q40 as pq

    out = {"dequant_mode": pq.DEQUANT_MODE}
    with _lock:
        if _sites:
            out["dequant_sites"] = dict(_sites)
        if _table is not None:
            out["dequant_table"] = dict(_table.provenance)
    return out


def bench_stamp(prefix: str) -> dict:
    """Phase-prefixed dequant attribution for BENCH_LIVE.json: every phase
    result records the resolved mode (and table provenance) next to its
    tok/s number so kernel A/B rows stay attributable after the fact."""
    s = dequant_stats()
    out = {f"{prefix}_dequant_mode": s["dequant_mode"]}
    if s.get("dequant_sites"):
        out[f"{prefix}_dequant_sites"] = s["dequant_sites"]
    if s.get("dequant_table"):
        t = s["dequant_table"]
        out[f"{prefix}_dequant_table"] = (
            f"v{t.get('version')}:{t.get('rows')} rows "
            f"({os.path.basename(t.get('path') or '?')}, "
            f"updated {t.get('updated')})"
        )
    return out


def record_win(d_in, d_out, m_class: str, mode: str, source: str,
               path: str | None = None) -> str:
    """Feed a measured (shape -> mode) winner back into the persisted
    table (scripts/evidence_loop.sh sweep phase, bench.py in-bench A/B,
    kernel_lab3 --adopt). Upserts the matching rule and rewrites the file
    atomically. Writes the FILE only: a live process's resolution stays
    whatever it froze at — the next serving start picks the row up."""
    from .pallas_q40 import DEQUANT_MODES

    if mode not in DEQUANT_MODES:
        raise ValueError(f"unknown dequant mode {mode!r}; one of {DEQUANT_MODES}")
    if m_class not in M_CLASSES + ("*",):
        raise ValueError(f"unknown m_class {m_class!r}; one of {M_CLASSES + ('*',)}")
    path = path or os.environ.get(_TABLE_ENV) or _DEFAULT_TABLE
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    else:
        data = {"version": 1, "provenance": "recorded by measurement loops",
                "rules": []}
    rules = data.setdefault("rules", [])
    for r in rules:
        if (r.get("d_in", "*"), r.get("d_out", "*"),
                r.get("m_class", "*")) == (d_in, d_out, m_class):
            r["mode"] = mode
            r["source"] = source
            break
    else:
        rules.append({"d_in": d_in, "d_out": d_out, "m_class": m_class,
                      "mode": mode, "source": source})
    data["updated"] = time.strftime("%Y-%m-%d")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return path
