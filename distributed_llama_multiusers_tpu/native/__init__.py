"""Native (C++) runtime components, bound via ctypes.

The reference implements its host-side runtime (quant codecs, weight
splitting, mmap IO) in C++ (src/nn/nn-quants.cpp, src/mmap.hpp); this package
provides the TPU framework's equivalents. The shared library is built by the
repo Makefile (`make native`) or on demand by :func:`ensure_built`; every
consumer falls back to the numpy codecs when the library is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from ..lockcheck import make_lock

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_DIR, "libdllama_native.so")
_SRC = os.path.join(_DIR, "quant_codec.cpp")

# witness-wrappable (DLLAMA_LOCKCHECK=1, lockcheck.py); module-level locks
# qualify by module stem in the static lock graph
_lock = make_lock("native._lock")
_lib: ctypes.CDLL | None = None
_load_failed = False


# single source of truth for the build lines; the Makefile targets shell out
# to this module so the paths cannot drift
BUILD_FLAGS = ["-O3", "-march=native", "-shared", "-fPIC", "-std=c++17"]
# ASan+UBSan build (the reference's test strategy leans on sanitizer CI,
# SURVEY.md §5.2): `make sanitize` builds this variant and runs the native
# test suite against it with libasan preloaded
SANITIZE_FLAGS = [
    "-O1", "-g", "-fno-omit-frame-pointer",
    "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
    "-shared", "-fPIC", "-std=c++17",
]
_SO_SAN_PATH = os.path.join(_DIR, "libdllama_native_asan.so")


def ensure_built(quiet: bool = True, sanitize: bool = False) -> bool:
    """Compile the shared library if missing/stale (g++). Returns success.
    Compiles to a per-pid temp file then renames, so concurrent first runs
    cannot corrupt the .so. ``sanitize`` builds the ASan+UBSan variant to
    its own path (load it via DLLAMA_NATIVE_SO with libasan preloaded)."""
    so_path = _SO_SAN_PATH if sanitize else _SO_PATH
    flags = SANITIZE_FLAGS if sanitize else BUILD_FLAGS
    try:
        if os.path.exists(so_path) and os.path.getmtime(so_path) >= os.path.getmtime(_SRC):
            return True
    except OSError:
        # source missing: usable iff a prebuilt .so is loadable
        return os.path.exists(so_path)
    tmp = f"{so_path}.{os.getpid()}.tmp"
    cmd = ["g++", *flags, "-o", tmp, _SRC, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=quiet)
        os.replace(tmp, so_path)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        # test hook: point at an alternate build (e.g. the sanitized .so)
        override = os.environ.get("DLLAMA_NATIVE_SO")
        # dlint: ok[lock-blocking] first-load compile is serialized behind the load lock on purpose: concurrent importers must block until one .so exists rather than race the compiler
        if not override and not ensure_built():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(override or _SO_PATH)
        except OSError:
            _load_failed = True
            return None
        c_f32p = ctypes.POINTER(ctypes.c_float)
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        c_i8p = ctypes.POINTER(ctypes.c_int8)
        c_u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.dlq_q40_quantize.argtypes = [c_f32p, c_u8p, ctypes.c_int64, ctypes.c_int]
        lib.dlq_q40_dequantize.argtypes = [c_u8p, c_f32p, ctypes.c_int64, ctypes.c_int]
        lib.dlq_q40_to_planar.argtypes = [c_u8p, c_i8p, c_f32p, ctypes.c_int64, ctypes.c_int]
        lib.dlq_q80_quantize.argtypes = [c_f32p, c_u8p, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
        lib.dlq_q80_dequantize.argtypes = [c_u8p, c_f32p, ctypes.c_int64, ctypes.c_int]
        lib.dlq_f16_to_f32.argtypes = [c_u16p, c_f32p, ctypes.c_int64, ctypes.c_int]
        lib.dlq_f32_to_f16.argtypes = [c_f32p, c_u16p, ctypes.c_int64, ctypes.c_int]
        lib.dlq_abi_version.restype = ctypes.c_int
        # version gate FIRST: a stale v1 build (or a DLLAMA_NATIVE_SO
        # override) must fall back cleanly, not AttributeError on symbols
        # that predate it
        if lib.dlq_abi_version() != 2:
            _load_failed = True
            return None
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        c_i64p = ctypes.POINTER(ctypes.c_int64)
        lib.dllama_bpe_create.argtypes = [
            c_u8p, c_i64p, ctypes.c_int32, ctypes.c_int32, c_f32p,
        ]
        lib.dllama_bpe_create.restype = ctypes.c_void_p
        lib.dllama_bpe_destroy.argtypes = [ctypes.c_void_p]
        lib.dllama_bpe_merge.argtypes = [
            ctypes.c_void_p, c_i32p, ctypes.c_int32, c_i32p,
        ]
        lib.dllama_bpe_merge.restype = ctypes.c_int32
        lib.dllama_bpe_encode.argtypes = [
            ctypes.c_void_p, c_u8p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int, c_i32p,
        ]
        lib.dllama_bpe_encode.restype = ctypes.c_int32
        _lib = lib
        return _lib


def _threads() -> int:
    return min(os.cpu_count() or 1, 16)


def available() -> bool:
    return load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def quantize_q40(x: np.ndarray) -> np.ndarray | None:
    lib = load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    assert x.size % 32 == 0
    n_blocks = x.size // 32
    out = np.empty((n_blocks, 18), np.uint8)
    lib.dlq_q40_quantize(_ptr(x, ctypes.c_float), _ptr(out, ctypes.c_uint8), n_blocks, _threads())
    return out


def dequantize_q40(blocks: np.ndarray) -> np.ndarray | None:
    lib = load()
    if lib is None:
        return None
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8).reshape(-1, 18)
    out = np.empty(blocks.shape[0] * 32, np.float32)
    lib.dlq_q40_dequantize(_ptr(blocks, ctypes.c_uint8), _ptr(out, ctypes.c_float), blocks.shape[0], _threads())
    return out


def q40_to_planar(blocks: np.ndarray):
    lib = load()
    if lib is None:
        return None
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8).reshape(-1, 18)
    n = blocks.shape[0]
    values = np.empty((n, 32), np.int8)
    scales = np.empty(n, np.float32)
    lib.dlq_q40_to_planar(
        _ptr(blocks, ctypes.c_uint8), _ptr(values, ctypes.c_int8), _ptr(scales, ctypes.c_float), n, _threads()
    )
    return values, scales


def quantize_q80(x: np.ndarray, mode: str = "runtime") -> np.ndarray | None:
    lib = load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    assert x.size % 32 == 0
    n_blocks = x.size // 32
    out = np.empty((n_blocks, 34), np.uint8)
    lib.dlq_q80_quantize(
        _ptr(x, ctypes.c_float), _ptr(out, ctypes.c_uint8), n_blocks,
        1 if mode == "converter" else 0, _threads(),
    )
    return out


def dequantize_q80(blocks: np.ndarray) -> np.ndarray | None:
    lib = load()
    if lib is None:
        return None
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8).reshape(-1, 34)
    out = np.empty(blocks.shape[0] * 32, np.float32)
    lib.dlq_q80_dequantize(_ptr(blocks, ctypes.c_uint8), _ptr(out, ctypes.c_float), blocks.shape[0], _threads())
    return out


class NativeBpe:
    """C++ BPE pair-merge context (tokenizer encode hot path). Holds the
    vocab/score tables native-side; ``merge`` is a single ctypes call per
    prompt. Token-identical to Tokenizer._merge (tests/test_native.py
    A/Bs them); falls back to None when the library is unavailable."""

    def __init__(self, vocab: list, regular_size: int, scores: list):
        lib = load()
        if lib is None:
            raise OSError("native library unavailable")
        concat = b"".join(vocab)
        buf = np.frombuffer(concat, np.uint8) if concat else np.zeros(1, np.uint8)
        offsets = np.zeros(len(vocab) + 1, np.int64)
        np.cumsum([len(v) for v in vocab], out=offsets[1:])
        sc = np.ascontiguousarray(scores, np.float32)
        self._lib = lib
        self._handle = lib.dllama_bpe_create(
            _ptr(np.ascontiguousarray(buf), ctypes.c_uint8),
            _ptr(offsets, ctypes.c_int64),
            len(vocab), regular_size,
            _ptr(sc, ctypes.c_float),
        )
        if not self._handle:
            raise OSError("dllama_bpe_create failed")

    def merge(self, ids: list) -> list:
        arr = np.ascontiguousarray(ids, np.int32)
        out = np.empty(max(len(arr), 1), np.int32)
        m = self._lib.dllama_bpe_merge(
            self._handle,
            _ptr(arr, ctypes.c_int32), len(arr),
            _ptr(out, ctypes.c_int32),
        )
        return out[:m].tolist()

    def encode(self, text: bytes, bos: int, add_special: bool):
        """Full scan+merge in one native call; None when the text has an
        untokenizable buffer (caller falls back to the Python encoder for
        the exact exception)."""
        data = np.frombuffer(text, np.uint8) if text else np.zeros(1, np.uint8)
        out = np.empty(len(text) + 1, np.int32)
        m = self._lib.dllama_bpe_encode(
            self._handle,
            _ptr(np.ascontiguousarray(data), ctypes.c_uint8), len(text),
            bos, int(add_special),
            _ptr(out, ctypes.c_int32),
        )
        if m < 0:
            return None
        return out[:m].tolist()

    def __del__(self):
        h = getattr(self, "_handle", None)
        if h:
            self._lib.dllama_bpe_destroy(h)
            self._handle = None
