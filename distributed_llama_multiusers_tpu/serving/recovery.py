"""Deterministic replay recovery: re-admit the journal's in-flight set.

The restart half of crash-durable serving. ``recover_scheduler`` reads
the request journal (serving/journal.py), takes every admitted request
without a finish record, and re-admits each one through the NORMAL
admission path — ``scheduler.submit()`` — on a background replay thread.
Three properties make this a latency blip instead of data loss:

- **byte-identical regeneration** — the journal carries the prompt
  tokens and the RESOLVED sampler seed; the scheduler regenerates from
  the prompt with the same ``fold_in(seed, pos)`` draws (the determinism
  class tests/test_sampler_parity.py pins), and prefix-cache re-prefill
  makes the recomputation cheap. The full regenerated stream buffers in
  the request's :class:`~.resume.StreamRelay` and the reconnecting
  client's ``Last-Event-ID`` picks the resume point, so it sees zero
  duplicated and zero lost tokens — even when the crash stranded
  written-but-never-received deltas in the dead process's socket buffer
  (the journaled watermark trails transport writes, not client receipt,
  so it can sit AHEAD of the client's true position and is never used
  to discard replayed deltas).
- **no recovery stampede** — re-admission is PACED (one request at a
  time, a small gap between submits) and goes through ``submit()``,
  which is gated by the circuit breaker: on a restart into a still-sick
  engine the breaker sheds the replay like any other client, and the
  replay retries with the breaker's own Retry-After hint — recovered
  work COMPOSES with the half-open probe instead of hammering a freshly
  restarted engine with the entire crash backlog at once.
- **containment** — a per-entry failure (or the ``recovery.replay``
  fault point) is counted and skipped; the replay never takes the
  serving loop down with it.

The coordinator is runtime-agnostic: request construction lives on the
scheduler (``build_recovered_request``), so this module — like the rest
of ``serving/`` — imports nothing from ``runtime/`` or ``server/``.
"""

from __future__ import annotations

import threading
import time

from ..lockcheck import make_lock
from ..utils import faults
from .journal import JournalEntry, read_journal
from .qos import AdmissionRejected

# per-entry re-admission gives up after this long of consecutive shed
# (breaker open / queue full): by then the backlog is stale anyway and
# the client has long since retried elsewhere
DEFAULT_ENTRY_DEADLINE_S = 120.0


def attach_recovered_stream(scheduler, entry: JournalEntry, registry=None):
    """Materialize one journal entry into a Request and — for streamed
    entries with a resume registry — register its relay, ready for
    ``scheduler.submit()``. Returns ``(request, registered)``.

    The single-entry body shared by the crash-replay thread below and
    the fleet migration endpoint (``POST /admin/migrate``,
    server/http.py): a router hands a live session's exported admit
    record to another replica, which regenerates it byte-identically
    through this exact path. The relay registers at ``base=0`` — NOT any
    journaled/exported watermark: a watermark trails the source's
    transport writes, not client receipt, so fast-forwarding through it
    would turn the client's honest ``Last-Event-ID`` into a resume_gap
    and lose the stranded deltas for good. The whole regenerated stream
    re-buffers (bounded by max_tokens — the regeneration happens anyway)
    and ``Last-Event-ID`` alone picks the resume point.

    Callers own the shed path: a ``submit()`` that raises must
    ``registry.discard(request.id)`` when ``registered`` is True, or the
    registry leaks one entry per shed."""
    req = scheduler.build_recovered_request(entry)
    registered = False
    if registry is not None and entry.stream:
        relay = registry.register(req, kind=entry.kind)
        registered = True
        # token index = consumed-token count at emit time
        req.on_delta = (
            lambda d, r=req, rel=relay: rel.push(
                len(r.generated_tokens), d
            )
        )
    return req, registered


class RecoveryCoordinator:
    """Owns the replay thread and the recovery counters /stats surfaces
    (scheduler.qos_stats merges ``stats()``; telemetry/hub bridges the
    fields to /metrics so the endpoints reconcile field-for-field)."""

    # dlint guarded-by declaration (analysis/lock_check.py): recovery
    # counters move under _lock — written by the replay thread, read by
    # /stats from HTTP threads.
    _dlint_guarded_by = {
        ("_lock",): (
            "_rc_recovered", "_rc_failed", "_rc_retries",
            "_rc_replayed_tokens", "_rc_done",
        ),
    }

    def __init__(self, scheduler, entries: list[JournalEntry],
                 registry=None, pace_s: float = 0.02,
                 entry_deadline_s: float = DEFAULT_ENTRY_DEADLINE_S):
        self.scheduler = scheduler
        self.entries = list(entries)
        self.registry = registry
        self.pace_s = float(pace_s)
        self.entry_deadline_s = float(entry_deadline_s)
        self.requests = []  # re-admitted Request objects, replay order
        self._lock = make_lock("RecoveryCoordinator._lock")
        self._rc_recovered = 0
        self._rc_failed = 0
        self._rc_retries = 0
        self._rc_replayed_tokens = 0
        self._rc_done = False
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="recovery-replay", daemon=True
        )

    def start(self) -> "RecoveryCoordinator":
        self._thread.start()
        return self

    # -- replay thread -------------------------------------------------------

    def _run(self) -> None:
        try:
            for entry in self.entries:
                if self._stop_evt.is_set():
                    break
                try:
                    faults.fire("recovery.replay")
                    self._replay_one(entry)
                except Exception:  # noqa: BLE001 — replay is contained
                    with self._lock:
                        self._rc_failed += 1
                if self.pace_s > 0:
                    # paced, stop-aware gap between re-admissions: the
                    # crash backlog trickles into the live queue instead
                    # of arriving as one thundering batch
                    self._stop_evt.wait(self.pace_s)
        finally:
            with self._lock:
                self._rc_done = True

    def _replay_one(self, entry: JournalEntry) -> None:
        scheduler = self.scheduler
        # base=0 re-buffer rule and the watermark argument live on
        # attach_recovered_stream — the body this thread shares with the
        # fleet migration endpoint
        req, registered = attach_recovered_stream(
            scheduler, entry, self.registry
        )
        deadline = time.monotonic() + self.entry_deadline_s
        while True:
            if self._stop_evt.is_set():
                # abandoned pre-submit: nothing will ever resolve the
                # future, so the registry entry must go or it leaks
                if registered:
                    self.registry.discard(req.id)
                return
            try:
                scheduler.submit(req)
                break
            except AdmissionRejected as shed:
                # breaker open / queue full on the fresh process: retry
                # on the shed's own hint — this is exactly the half-open
                # probe window composing with recovery
                if time.monotonic() >= deadline:
                    if registered:
                        self.registry.discard(req.id)
                    with self._lock:
                        self._rc_failed += 1
                    return
                with self._lock:
                    self._rc_retries += 1
                self._stop_evt.wait(
                    min(max(shed.retry_after_s, 0.05), 2.0)
                )
        self.requests.append(req)
        with self._lock:
            self._rc_recovered += 1
            self._rc_replayed_tokens += entry.watermark

    # -- surfaces ------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "recovery_incomplete": len(self.entries),
                "recovered_requests": self._rc_recovered,
                "recovery_failed": self._rc_failed,
                "recovery_retries": self._rc_retries,
                "recovery_replayed_tokens": self._rc_replayed_tokens,
                "recovery_done": self._rc_done,
            }

    def join(self, timeout: float | None = None) -> bool:
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def stop(self, timeout: float | None = 5.0) -> None:
        self._stop_evt.set()
        if self._thread.is_alive():
            self._thread.join(timeout)


def recover_scheduler(scheduler, journal_path: str, registry=None,
                      pace_s: float = 0.02) -> RecoveryCoordinator:
    """Read ``journal_path`` and start replaying its incomplete requests
    into ``scheduler``. Returns the started coordinator (attached as
    ``scheduler.recovery`` so /stats picks the counters up). Stream
    reattachment needs a ``registry`` (serving/resume.py) — without one,
    recovered requests still regenerate and journal their finish (so a
    second restart does not resurrect them again), but emitted deltas
    have nowhere to go."""
    image = read_journal(journal_path)
    coordinator = RecoveryCoordinator(
        scheduler, image.incomplete(), registry=registry, pace_s=pace_s
    )
    coordinator.image = image
    scheduler.recovery = coordinator
    return coordinator.start()
