"""Pipeline parallelism: GPipe-style microbatch schedule over the pp axis.

The reference explicitly has NO pipeline parallelism — its paper contrasts
the TP design with Petals/llama.cpp-MPI layer splitting (SURVEY.md §2.4) —
so this is a capability extension, built TPU-first as a pure-GSPMD program
(the schedule XLA's SPMD partitioner was designed for, no manual
collectives):

- The [n_layers] stack reshapes to [pp, n_layers/pp, ...] and shards its
  stage axis over ``pp``; each device holds n_layers/pp consecutive layers.
- The batch splits into M microbatches. One tick = every stage running its
  layer block on its current microbatch simultaneously — expressed as a
  ``vmap`` over the stage axis, which XLA partitions across pp.
- Between ticks, activations hop stage-to-stage via ``jnp.roll`` on the
  stage axis; on a pp-sharded array XLA lowers this to a CollectivePermute
  over ICI. Over M + pp - 1 ticks every microbatch visits every stage
  (stage d sees microbatch s - d at tick s): the GPipe fill/drain schedule.
- dp/tp/ep compose freely: inside a tick the per-stage compute is ordinary
  GSPMD, so tensor-parallel weights keep their tp sharding and the usual
  psum at wo/w2 boundaries. (sp ring attention does not nest — stages run
  dense attention; pp+sp remain separate meshes, see __graft_entry__.)

Embedding and the final norm/logits run outside the pipeline under plain
GSPMD; only the layer stack is staged. Everything differentiates — the
backward pass is the same schedule transposed, with reversed hops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import LlamaConfig
from ..models.llama import LlamaParams, train_layer_step_fn
from ..ops.linear import matmul
from ..ops.norm import rms_norm


def pipeline_forward_train(
    config: LlamaConfig,
    params: LlamaParams,
    tokens: jnp.ndarray,  # [B, T] int32
    mesh: Mesh,
    n_microbatches: int | None = None,
) -> jnp.ndarray:
    """Causal full-sequence forward with the layer stack pipelined over pp.
    Returns logits [B, T, vocab] f32; matches llama_forward_train exactly."""
    n_pp = mesh.shape["pp"]
    b, t = tokens.shape
    if n_pp <= 1:
        from ..models.llama import llama_forward_train

        return llama_forward_train(config, params, tokens, mesh=mesh)
    m = n_microbatches or n_pp
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    if config.n_layers % n_pp != 0:
        raise ValueError(f"n_layers={config.n_layers} not divisible by pp={n_pp}")
    mb = b // m

    def act_sharded(x):
        # activations: stage axis over pp, microbatch lanes over dp
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("pp", "dp"))
        )

    # [L, ...] -> [pp, L/pp, ...], stage axis sharded over pp with each
    # weight's own trailing spec (tp/ep factors) preserved — the reshape is a
    # relabeling of the already-P("pp", ...)-sharded layer axis
    # (parallel/sharding.py), not a reshuffle.
    from .sharding import param_shardings

    layer_specs = param_shardings(mesh, params).layers

    def to_stage(w, s):
        spec = s.spec
        staged = w.reshape(n_pp, config.n_layers // n_pp, *w.shape[1:])
        return jax.lax.with_sharding_constraint(
            staged, NamedSharding(mesh, P(spec[0], None, *spec[1:]))
        )

    stages = jax.tree.map(to_stage, params.layers, layer_specs)

    x = params.embedding[tokens]  # [B, T, dim] — plain GSPMD
    xmb = jax.lax.with_sharding_constraint(
        x.reshape(m, mb, t, x.shape[-1]), NamedSharding(mesh, P(None, "dp"))
    )
    layer_step = train_layer_step_fn(
        config, params.rope_cos, params.rope_sin,
        ep_sharded=mesh.shape.get("ep", 1) > 1,
    )

    def stage_fn(layers_local, xin):
        return jax.lax.scan(layer_step, xin, layers_local)[0]

    # one tick: all pp stages run their layer block at once; XLA partitions
    # the vmapped compute along the sharded stage axis
    vstage = jax.vmap(stage_fn, in_axes=(0, 0))

    state = act_sharded(jnp.zeros((n_pp, mb, t, x.shape[-1]), x.dtype))
    outs = jnp.zeros((m, mb, t, x.shape[-1]), x.dtype)
    # GPipe fill/drain: M + pp - 1 ticks, stage d works microbatch s - d.
    # s is a Python int, so injection/collection are static slices.
    for s in range(m + n_pp - 1):
        if s < m:
            state = state.at[0].set(xmb[s])
        y = act_sharded(vstage(stages, state))  # [pp, mb, t, dim]
        out_idx = s - (n_pp - 1)
        if out_idx >= 0:
            outs = outs.at[out_idx].set(y[-1])  # drain the last stage
        # hop: stage i's output becomes stage i+1's input — on the pp-sharded
        # axis this is the CollectivePermute the reference built from TCP
        # socket writes (src/nn/nn-network.cpp:537-569)
        state = jnp.roll(y, 1, axis=0)

    x = outs.reshape(b, t, -1)
    y = rms_norm(x, params.rms_final, config.norm_epsilon)
    # wcls may be padded past vocab_size (quants/packed.pad_packed_d_out);
    # slice like llama_forward_train so the twins stay logit-identical
    logits = matmul(y, params.wcls).astype(jnp.float32)
    return logits[..., : config.vocab_size]
