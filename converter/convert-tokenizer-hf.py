#!/usr/bin/env python
"""Convert a HuggingFace fast tokenizer (tokenizer.json + tokenizer_config.json)
to the `.t` format.

Usage: python convert-tokenizer-hf.py <sourceFolderPath> <name>

Reimplementation of the reference (converter/convert-tokenizer-hf.py): the
GPT-2 unicode<->byte table maps the BPE vocab's printable-unicode encoding
back to raw bytes; merge ranks become negative scores so the runtime's
best-score merge reproduces HF merge order; special/added tokens go after
bos (the regular/special split point, src/tokenizer.cpp:137-139 assumption).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_llama_multiusers_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer_file


def gpt2_byte_decoder() -> dict[str, int]:
    """The printable-unicode <-> byte bijection used by byte-level BPE."""
    bs = list(range(ord("!"), ord("~") + 1)) + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def token_to_bytes(token: str, byte_decoder: dict[str, int]) -> bytes:
    try:
        return bytes(byte_decoder[ch] for ch in token)
    except KeyError:
        # not byte-level-encoded (e.g. sentencepiece-style metaspace)
        return token.replace("▁", " ").encode("utf-8")


def convert(folder: str, out_path: str) -> TokenizerData:
    with open(os.path.join(folder, "tokenizer.json")) as f:
        tok = json.load(f)
    config = {}
    cfg_path = os.path.join(folder, "tokenizer_config.json")
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            config = json.load(f)

    model = tok["model"]
    if model.get("type") != "BPE":
        raise ValueError(f"Unsupported tokenizer model type {model.get('type')}")
    vocab_map: dict[str, int] = model["vocab"]
    merges = model.get("merges", [])
    byte_decoder = gpt2_byte_decoder()

    n_regular = len(vocab_map)
    vocab: list[bytes] = [b"?"] * n_regular
    scores: list[float] = [0.0] * n_regular
    for token, tid in vocab_map.items():
        vocab[tid] = token_to_bytes(token, byte_decoder)
    # merge rank -> score: earlier merges must win, and all merges must beat
    # the zero default, so score = nMerges - rank (reference uses the same idea)
    for rank, merge in enumerate(merges):
        pair = merge.split(" ") if isinstance(merge, str) else merge
        merged = "".join(pair)
        tid = vocab_map.get(merged)
        if tid is not None:
            scores[tid] = float(len(merges) - rank)

    added = sorted(tok.get("added_tokens", []), key=lambda t: t["id"])
    specials = [(t["id"], t["content"].encode("utf-8")) for t in added if t["id"] >= n_regular]
    for tid, content in specials:
        while len(vocab) <= tid:
            vocab.append(b"<|pad_%d|>" % len(vocab))
            scores.append(0.0)
        vocab[tid] = content
        scores[tid] = 0.0

    def find_id(name: str | dict | None) -> int | None:
        if name is None:
            return None
        if isinstance(name, dict):
            name = name.get("content")
        b = name.encode("utf-8")
        for tid, content in specials:
            if content == b:
                return tid
        try:
            return vocab.index(b)
        except ValueError:
            return None

    bos_id = find_id(config.get("bos_token"))
    eos_id = find_id(config.get("eos_token"))
    if bos_id is None:
        bos_id = min((tid for tid, _ in specials), default=n_regular)
    eos_ids = [eos_id] if eos_id is not None else []
    eot = find_id("<|eot_id|>")
    if eot is not None and eot not in eos_ids:
        eos_ids.append(eot)

    data = TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id,
        eos_token_ids=eos_ids,
        chat_template=config.get("chat_template"),
    )
    with open(out_path, "wb") as f:
        write_tokenizer_file(f, data)
    print(f"✅ {out_path}: vocab {len(vocab)}, bos {bos_id}, eos {eos_ids}")
    return data


def main() -> None:
    if len(sys.argv) < 3:
        print("Usage: python convert-tokenizer-hf.py <sourceFolderPath> <name>")
        raise SystemExit(1)
    convert(sys.argv[1], f"dllama_tokenizer_{sys.argv[2]}.t")


if __name__ == "__main__":
    main()
