"""dlint v5 checks: resource-balance and device-affinity.

Both consume the resource-lifecycle surface model
(analysis/resourcemodel.py) and emit from ``finalize`` — the analyses
are cross-file by construction (transitive releasers span modules, call
sites of a leaky function live anywhere), so like lock-order they only
exist once every file has been seen. A pleasant corollary: ``--changed``
runs always report them in full, because finalize findings are never
filtered to the changed set.

**resource-balance** — every function that directly acquires a declared
resource kind (calls a ``_dlint_acquires`` method) must not let an
exception escape with the resource still held. A ``raise`` lexically
after the first acquire is a finding unless one of these holds:

1. it sits in an ``except`` arm of the try whose BODY contains the
   acquire itself (the acquire may be what failed — nothing is held);
2. a release of the kind (directly or via any transitive releaser
   wrapper, e.g. ``_fail_request`` -> ``_paged_release`` ->
   ``paged_finish``) appears lexically between the acquire and the
   raise;
3. the raise is in the BODY of a try one of whose handlers calls a
   releaser of the kind (cleanup-at-catch);
4. interprocedural: the function has at least one call site in the
   package and EVERY call site sits inside a try whose handler calls a
   transitive releaser of the kind — the owner one frame up releases on
   failure (the scheduler's ``_claim_next`` / ``_start_request`` shape);
5. an ``ok[resource-balance]`` waiver marks the raise as an intentional
   transfer (park hand-off, migration ticket).

A plain ``return`` is never flagged: returning an acquired resource IS
ownership transfer, the normal API shape (``register`` returning its
relay, ``paged_admit`` returning the prefix start).

**device-affinity** — calls to ``_dlint_device_affine`` methods (the
donated-device-pytree touchers) are legal only:

1. inside the file that declared them (the engine façade calls its own
   halves);
2. inside a lambda passed to ``scheduler.run_device_op`` (or a local
   alias of it) — the sanctioned cross-thread funnel;
3. from a method in the batching-loop closure (the ``_dlint_loop_roots``
   fixpoint over same-class ``self.X()`` calls);
4. from an engine-facade class — one that defines at least one
   same-named device-affine method itself (the pod's RootControlEngine
   proxies replicate every device call to workers; the scheduler holds
   the facade AS its engine, so facade method bodies run exactly where
   the declaring engine's do);
5. from a function whose EVERY package call site is itself legal under
   these rules (the disagg export/import helpers, reached only through
   ``run_device_op`` lambdas);
6. under an ``ok[device-affinity]`` waiver (the pod worker's replay
   loop IS its host's batching thread).

This mechanizes the race PR 16 caught live: an admin/HTTP thread
touching ``engine.cache`` while the loop's next dispatch has already
donated it.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, Project, SourceFile, last_component, waived
from .resourcemodel import (
    CallSite,
    FuncInfo,
    ResourceModel,
    ingest_file,
    project_model,
)


class ResourceBalanceChecker(Checker):
    name = "resource-balance"
    description = (
        "every acquire of a declared resource kind must be released on "
        "all exception paths (transfers via return are ownership moves; "
        "intentional transfers at a raise need ok[resource-balance])"
    )

    def collect(self, sf: SourceFile, project: Project) -> None:
        ingest_file(project_model(project), sf, project)

    def finalize(self, project: Project):
        model = project_model(project)
        for kind in sorted(model.kinds):
            decl = model.kinds[kind]
            if not decl.acquires or not decl.releases:
                # half-declared vocabulary: nothing can ever balance, so
                # flag the declaration rather than every acquire site
                site = next(
                    iter(decl.acquires.values()),
                    next(iter(decl.releases.values()), "?"),
                )
                yield Finding(
                    self.name, site.split("(", 1)[-1].rstrip(")"), 0,
                    f"resource kind {kind!r} declares "
                    f"{'no acquire' if not decl.acquires else 'no release'}"
                    " methods — pair _dlint_acquires with _dlint_releases",
                )
                continue
            vocab = decl.vocabulary
            acquire_names = frozenset(decl.acquires)
            releasers = model.transitive_releasers(kind)
            for fn in model.functions:
                if fn.name in vocab:
                    continue  # vocabulary implementations and proxies
                acq = [c for c in fn.calls if c.name in acquire_names]
                if not acq:
                    continue
                first_acq = min(c.line for c in acq)
                acq_name = min(acq, key=lambda c: c.line).name
                release_lines = [
                    c.line for c in fn.calls if c.name in releasers
                ]
                sites_excused = None  # computed lazily, once per fn/kind
                for rs in fn.raises:
                    if rs.line <= first_acq:
                        continue
                    if self._handler_of_acquire_try(rs, acq):
                        continue
                    if any(first_acq < rl < rs.line for rl in release_lines):
                        continue
                    if self._releasing_handler_below(model, rs, releasers):
                        continue
                    if sites_excused is None:
                        sites_excused = self._call_sites_release(
                            model, fn, releasers
                        )
                    if sites_excused:
                        continue
                    yield Finding(
                        self.name, fn.path, rs.line,
                        f"'{fn.qual}' raises with a {kind} acquired via "
                        f"{acq_name}() still held — no release reaches "
                        "this exception path (release it, or waive an "
                        "intentional transfer with ok[resource-balance])",
                    )

    @staticmethod
    def _handler_of_acquire_try(rs, acq: list[CallSite]) -> bool:
        """Excuse 1: the raise's own except arm belongs to the try whose
        body holds the acquire — the acquire itself may have failed."""
        t = rs.handler_try
        if t is None or not t.handlers:
            return False
        body_start = t.body[0].lineno
        body_end = t.handlers[0].lineno
        return any(body_start <= c.line < body_end for c in acq)

    @staticmethod
    def _handler_calls(model: ResourceModel, t, names: frozenset[str] | set[str]) -> bool:
        for h in t.handlers:
            for node in ast.walk(h):
                if isinstance(node, ast.Call):
                    if last_component(node.func) in names:
                        return True
        return False

    def _releasing_handler_below(self, model, rs, releasers) -> bool:
        """Excuse 3: some enclosing try will catch this raise and its
        handler releases the kind."""
        return any(
            self._handler_calls(model, t, releasers) for t in rs.body_trys
        )

    def _call_sites_release(
        self, model: ResourceModel, fn: FuncInfo, releasers: set[str]
    ) -> bool:
        """Excuse 4: every package call site of ``fn`` sits inside a try
        whose handler transitively releases the kind."""
        sites = [
            c
            for g in model.functions
            if g is not fn
            for c in g.calls
            if c.name == fn.name
        ]
        if not sites:
            return False
        return all(
            any(self._handler_calls(model, t, releasers) for t in c.body_trys)
            for c in sites
        )


class DeviceAffinityChecker(Checker):
    name = "device-affinity"
    description = (
        "_dlint_device_affine methods (donated device pytree touchers) "
        "may only run on the batching loop or through "
        "scheduler.run_device_op()"
    )

    def collect(self, sf: SourceFile, project: Project) -> None:
        ingest_file(project_model(project), sf, project)

    def finalize(self, project: Project):
        model = project_model(project)
        if not model.device_methods:
            return
        closures = {
            key: model.loop_closure(*key) for key in model.loop_roots
        }

        def in_closure(fn: FuncInfo) -> bool:
            return (
                fn.cls is not None
                and fn.name in closures.get((fn.path, fn.cls), ())
            )

        def call_waived(fn: FuncInfo, c: CallSite) -> bool:
            sf = model.files.get(fn.path)
            if sf is None:
                return False
            return waived(sf, Finding(self.name, fn.path, c.line, ""))

        # engine facades: classes defining any declared device-affine
        # method are part of the engine surface itself (RootControlEngine,
        # test engines) — their method bodies inherit the engine's
        # affinity contract, since callers reach them through the same
        # `engine.X()` dispatch the declaring engine gets
        facades = {
            (path, cls)
            for path, classes in model.class_methods.items()
            for cls, methods in classes.items()
            if methods & set(model.device_methods)
        }

        def direct_ok(fn: FuncInfo, c: CallSite) -> bool:
            if fn.path in model.device_decl_paths:
                return True
            if c.in_funnel_arg:
                return True
            if in_closure(fn):
                return True
            if fn.cls is not None and (fn.path, fn.cls) in facades:
                return True
            return False

        # offending device calls, grouped by containing function
        offenders: dict[str, list[tuple[FuncInfo, CallSite]]] = {}
        for fn in model.functions:
            for c in fn.calls:
                if c.name not in model.device_methods:
                    continue
                if direct_ok(fn, c):
                    continue
                offenders.setdefault(fn.name, []).append((fn, c))

        # caller-legality fixpoint (rule 5): a function whose every
        # package call site is itself in a legal context inherits
        # legality — waived call sites count (the waiver carries the
        # justification)
        legal_funcs: set[str] = set()
        changed = True
        while changed:
            changed = False
            for name in list(offenders):
                if name in legal_funcs:
                    continue
                sites = [
                    (g, c)
                    for g in model.functions
                    for c in g.calls
                    if c.name == name and g.name != name
                ]
                if sites and all(
                    direct_ok(g, c) or g.name in legal_funcs
                    or call_waived(g, c)
                    for g, c in sites
                ):
                    legal_funcs.add(name)
                    changed = True

        for name in sorted(offenders):
            if name in legal_funcs:
                continue
            for fn, c in offenders[name]:
                yield Finding(
                    self.name, fn.path, c.line,
                    f"'{c.name}' called from '{fn.qual}' off the batching "
                    "loop — donated device pytrees may only be touched on "
                    "the loop thread or through scheduler.run_device_op() "
                    f"(declared device-affine by "
                    f"{model.device_methods[c.name]})",
                )
