"""Finding renderers: the default ``file:line`` text plus CI formats.

- ``github`` — GitHub Actions workflow commands (``::error file=...``):
  every finding becomes an inline annotation on the PR diff. ``make
  lint`` selects this automatically when ``GITHUB_ACTIONS=true``.
- ``sarif`` — SARIF 2.1.0, the interchange format code-scanning UIs
  ingest (one run, one rule per check, one result per finding).

Pure stdlib (json), same as the rest of the analyzer.
"""

from __future__ import annotations

import json
from typing import Iterable

from .core import Checker, Finding

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def render_text(findings: Iterable[Finding]) -> list[str]:
    return [f.render() for f in findings]


def _gh_escape(s: str) -> str:
    """Workflow-command data escaping (the %, CR, LF triple GitHub
    documents; properties additionally escape , and :)."""
    return s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def render_github(findings: Iterable[Finding]) -> list[str]:
    out = []
    for f in findings:
        path = _gh_escape(f.path).replace(",", "%2C").replace(":", "%3A")
        out.append(
            f"::error file={path},line={max(1, f.line)},"
            f"title=dlint[{f.check}]::{_gh_escape(f.message)}"
        )
    return out


def render_sarif(
    findings: Iterable[Finding], checkers: Iterable[Checker]
) -> list[str]:
    rules = [
        {
            "id": c.name,
            "shortDescription": {"text": c.description or c.name},
        }
        for c in checkers
    ]
    rules.append({
        "id": "waiver",
        "shortDescription": {
            "text": "waiver syntax: reasons mandatory, names known"
        },
    })
    rules.append({
        "id": "parse",
        "shortDescription": {"text": "file could not be analyzed"},
    })
    results = [
        {
            "ruleId": f.check,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        for f in findings
    ]
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "dlint",
                "informationUri": "docs/LINT.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }
    return [json.dumps(doc, indent=2)]
