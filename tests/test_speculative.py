"""Prompt-lookup speculative decoding (capability extension — the reference
has nothing comparable; src/app.cpp:314-402 decodes strictly one token per
forward per lane).

The invariant under test is the speculative-verification identity: greedy
lanes must emit EXACTLY the token stream plain decode would produce — drafts
only change how many forwards that stream costs. Cache correctness after a
spec step matters as much as the emitted tokens: the accepted prefix's KV
writes come from the verify forward itself.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer


@pytest.fixture(scope="module")
def loaded(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    return config, params, tok


def _fresh_engine(config, params, n_lanes=2):
    return InferenceEngine(config, params, n_lanes=n_lanes, prefill_buckets=(4,))


def _greedy_rollout(engine, prompt, n):
    """Plain greedy decode of n tokens on lane 0; returns produced tokens."""
    from distributed_llama_multiusers_tpu.utils.testing import greedy_rollout

    toks, _ = greedy_rollout(engine, prompt, n)
    return toks


def test_spec_accepts_correct_drafts(loaded):
    """A draft equal to the greedy continuation is fully accepted, the
    emitted tokens match plain decode, and the cache state after the spec
    step supports identical further decoding."""
    config, params, tok = loaded
    prompt = [5, 9, 3]
    ref = _greedy_rollout(_fresh_engine(config, params), prompt, 7)

    engine = _fresh_engine(config, params)
    _, g0, pos = engine.prefill(0, prompt)
    assert int(g0) == ref[0]
    k = engine.SPEC_DRAFT
    tokens = np.zeros(engine.n_lanes, np.int32)
    positions = np.zeros(engine.n_lanes, np.int32)
    drafts = np.zeros((engine.n_lanes, k), np.int32)
    dlen = np.zeros(engine.n_lanes, np.int32)
    tokens[0], positions[0] = ref[0], pos
    drafts[0] = ref[1 : 1 + k]
    dlen[0] = k
    _, emitted, n_emit = engine.decode_spec(tokens, drafts, dlen, positions)
    assert int(n_emit[0]) == k + 1  # every draft accepted + the bonus token
    assert [int(t) for t in emitted[0]] == ref[1 : k + 2]

    # the spec step's KV writes must be the real thing: continue plain
    # decoding from the accepted state and match the reference stream
    pos += k + 1
    tokens[0], positions[0] = ref[k + 1], pos
    _, greedy, _ = engine.decode(tokens, positions)
    assert int(greedy[0]) == ref[k + 2]


def test_spec_rejects_wrong_drafts(loaded):
    """A mismatching draft yields exactly the plain-decode token and nothing
    else (n_emit == 1)."""
    config, params, tok = loaded
    prompt = [5, 9, 3]
    ref = _greedy_rollout(_fresh_engine(config, params), prompt, 5)

    engine = _fresh_engine(config, params)
    _, _, pos = engine.prefill(0, prompt)
    k = engine.SPEC_DRAFT
    tokens = np.zeros(engine.n_lanes, np.int32)
    positions = np.zeros(engine.n_lanes, np.int32)
    drafts = np.zeros((engine.n_lanes, k), np.int32)
    dlen = np.zeros(engine.n_lanes, np.int32)
    tokens[0], positions[0] = ref[0], pos
    drafts[0] = [(t + 1) % config.vocab_size for t in ref[1 : 1 + k]]  # wrong
    dlen[0] = k
    _, emitted, n_emit = engine.decode_spec(tokens, drafts, dlen, positions)
    assert int(n_emit[0]) == 1
    assert int(emitted[0, 0]) == ref[1]


def _run_requests(engine, tok, reqs, **kw):
    sched = ContinuousBatchingScheduler(engine, tok, **kw)
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated_tokens) for r in reqs]


def test_scheduler_spec_matches_plain(loaded, monkeypatch):
    """End-to-end scheduler parity: the same mixed batch (greedy + seeded
    sampled) generates identical token streams with speculation on and off."""
    config, params, tok = loaded

    def reqs():
        return [
            Request(prompt="hello world hello world hello", max_tokens=12,
                    temperature=0.0),
            Request(prompt="aa bb aa bb aa", max_tokens=10, temperature=0.0),
            Request(prompt="sampled one", max_tokens=8, temperature=0.8,
                    seed=123),
        ]

    spec_engine = _fresh_engine(config, params, n_lanes=4)
    got_spec = _run_requests(spec_engine, tok, reqs())
    assert spec_engine.stats.spec_steps > 0

    plain_engine = _fresh_engine(config, params, n_lanes=4)
    monkeypatch.setattr(
        type(plain_engine), "supports_speculative", False, raising=True
    )
    try:
        got_plain = _run_requests(plain_engine, tok, reqs())
    finally:
        monkeypatch.undo()
    assert got_spec == got_plain


def test_scheduler_spec_near_seq_len(loaded):
    """Lanes approaching seq_len must fall back to plain decode instead of
    scribbling past the end; generation completes cleanly at the length
    cap."""
    config, params, tok = loaded
    engine = _fresh_engine(config, params, n_lanes=2)
    r = Request(prompt="aa bb aa bb", max_tokens=config.seq_len,
                temperature=0.0)
    out = _run_requests(engine, tok, [r])[0]
    assert r.finish_reason in ("length", "stop")
    assert len(out) >= 1


def test_pod_root_engine_broadcasts_spec():
    """RootControlEngine supports speculation by broadcasting an
    OP_DECODE_SPEC packet before the root-side verify call, so workers
    replay the identical program (no silent direct dispatch)."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_DECODE_SPEC,
        ControlPlane,
        RootControlEngine,
    )

    assert InferenceEngine.supports_speculative is True

    sent = []

    class _Plane(ControlPlane):
        def _bcast(self, pkt):
            sent.append(np.array(pkt))
            return pkt

    class _Inner:
        n_lanes = 2
        SPEC_DRAFT = 3
        supports_speculative = True

        def decode_spec(self, tokens, drafts, draft_len, positions,
                        temps=None, topps=None, seeds=None,
                        g_states=None):
            return "logits", np.zeros((2, 4), np.int32), np.ones(2, np.int32)

    plane = _Plane(n_lanes=2, chunk=8)
    root = RootControlEngine(_Inner(), plane)
    assert root.supports_speculative  # forwarded from the inner engine
    tokens = np.array([1, 2], np.int32)
    drafts = np.array([[3, 4, 5], [6, 7, 8]], np.int32)
    dlen = np.array([3, 0], np.int32)
    root.decode_spec(tokens, drafts, dlen, tokens)
    # header: [magic, version, op, ...] — op rides slot 2 since the
    # packet-integrity words landed
    assert len(sent) == 1 and sent[0][2] == OP_DECODE_SPEC
    # the worker-side decode reconstructs the drafts from slots 5/6
    assert list(plane.slot(sent[0], 5, 6)) == [3, 4, 5, 6, 7, 8]
    assert list(plane.slot(sent[0], 6, 2)) == [3, 0]


def test_scheduler_spec_gates_per_lane(loaded):
    """A lane near seq_len must NOT disable speculation for the whole
    batch (round-4 weak #4: the old global all() gate did): while lane 0
    sits within SPEC_DRAFT slots of seq_len, other lanes keep drafting,
    and lane 0's own drafts are clamped to its remaining slots.

    Pinned on the SYNCHRONOUS spec path (pipelined=False): with the
    zero-flush chain the host no longer clamps — the verify program
    clamps on device from the carried positions (pinned at engine level
    in tests/test_spec_pipelined.py)."""
    config, params, tok = loaded
    k = InferenceEngine.SPEC_DRAFT
    # a prompt that prefills lane 0 to within k slots of seq_len (old gate
    # territory: pos + k + 1 > seq_len) while still allowed to generate;
    # the synthetic tokenizer is char-level (one token per char + BOS)
    long_prompt = "a" * (config.seq_len - 3)
    n_long = len(tok.encode(long_prompt))
    assert config.seq_len - k <= n_long <= config.seq_len - 2, (
        f"long prompt landed at {n_long} tokens; expected within "
        f"[{config.seq_len - k}, {config.seq_len - 2}]"
    )

    def reqs():
        return [
            Request(prompt=long_prompt, max_tokens=8, temperature=0.0),
            Request(prompt="aa bb aa bb aa bb aa bb aa", max_tokens=50,
                    temperature=0.0),
        ]

    engine = _fresh_engine(config, params, n_lanes=2)
    calls = []
    real = engine.decode_spec

    def spy(tokens, drafts, draft_len, positions, *a, **kw):
        calls.append((np.array(positions), np.array(draft_len)))
        return real(tokens, drafts, draft_len, positions, *a, **kw)

    engine.decode_spec = spy
    got_spec = _run_requests(engine, tok, reqs(), pipelined=False)

    near_end = [
        (pos, dlen) for pos, dlen in calls if pos[0] >= config.seq_len - k
    ]
    assert near_end, "no spec step ran while lane 0 was near seq_len"
    for pos, dlen in calls:
        for lane in range(2):
            assert dlen[lane] <= max(0, config.seq_len - pos[lane] - 1)
    assert any(dlen[1] > 0 for _, dlen in near_end), (
        "lane 1 stopped drafting while lane 0 was near seq_len"
    )

    # clamped partial drafts keep the exact plain-decode streams
    import unittest.mock as mock

    plain_engine = _fresh_engine(config, params, n_lanes=2)
    with mock.patch.object(
        type(plain_engine), "supports_speculative", False
    ):
        got_plain = _run_requests(plain_engine, tok, reqs(), pipelined=False)
    assert got_spec == got_plain


def test_spec_stream_emits_plain_stream_with_fewer_forwards(loaded):
    """SpecStream (the single-stream helper behind inference AND chat
    mode) emits exactly the plain greedy stream while spending fewer
    forwards on draftable output; near seq_len it clamps instead of
    overshooting."""
    from distributed_llama_multiusers_tpu.runtime.spec import SpecStream

    config, params, tok = loaded
    prompt = tok.encode("aa bb aa bb aa bb aa bb")
    n = 40

    ref_engine = _fresh_engine(config, params, n_lanes=1)
    ref = _greedy_rollout(ref_engine, prompt, n)

    engine = _fresh_engine(config, params, n_lanes=1)
    _, g0, pos = engine.prefill(0, prompt)
    spec = SpecStream(engine, config, enabled=True, prompt_tokens=prompt)
    cur, out, forwards = int(g0), [int(g0)], 0
    while len(out) < n and pos < config.seq_len - 1:
        nxt, used_forward = spec.advance(cur, pos)
        forwards += used_forward
        pos += 1
        cur = nxt
        out.append(cur)
    assert out == ref[: len(out)]
    assert forwards < len(out) - 1, (
        f"speculation never accepted a draft ({forwards} forwards for "
        f"{len(out)} tokens on repetitive output)"
    )


def test_spec_stream_multi_step_fallback(loaded):
    """With multi_h set, draft-less greedy steps chain a horizon of plain
    decodes: the emitted stream is still EXACTLY the plain greedy stream,
    dispatches drop well below one per token, and multi-step pending
    tokens do NOT count toward the speculation acceptance stats."""
    from distributed_llama_multiusers_tpu.runtime.spec import SpecStream

    config, params, tok = loaded
    prompt = tok.encode("one two three four")
    n = 24

    ref_engine = _fresh_engine(config, params, n_lanes=1)
    ref = _greedy_rollout(ref_engine, prompt, n)

    engine = _fresh_engine(config, params, n_lanes=1)
    _, g0, pos = engine.prefill(0, prompt)
    engine.stats.reset()
    # spec disabled (no drafter): isolates the multi-step path
    spec = SpecStream(engine, config, enabled=False, multi_h=4)
    cur, out, forwards = int(g0), [int(g0)], 0
    while len(out) < n and pos < config.seq_len - 1:
        nxt, used_forward = spec.advance(cur, pos)
        forwards += used_forward
        pos += 1
        cur = nxt
        out.append(cur)
    assert out == ref[: len(out)]
    assert forwards <= (len(out) + 3) // 4 + 1, (
        f"{forwards} dispatches for {len(out)} tokens at multi_h=4"
    )
    assert engine.stats.multi_dispatches > 0
    assert engine.stats.spec_emitted == 0  # multi tokens aren't "accepted"
    assert engine.stats.spec_lane_steps == 0
