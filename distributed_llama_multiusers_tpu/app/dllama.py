"""`dllama` CLI: inference | chat | worker (reference: src/dllama.cpp).

- inference: prompt eval + N-token generation with per-token Eval/Pred
  timing and a tok/s summary (src/dllama.cpp:36-113's 🔶/Evaluation/
  Prediction readout).
- chat: interactive chat with template rendering and streamed,
  stop-string-gated output (src/dllama.cpp:130-214).
- worker: joins a jax.distributed pod (--coordinator/--num-processes/
  --process-id) and replays root-broadcast engine calls until the root
  sends stop — the SPMD analogue of the reference's TCP worker that
  receives its program and control packets from the root
  (src/app.cpp:405-464). Without coordinator flags it prints mesh guidance
  and exits (single-host chips join via --workers N instead).
"""

from __future__ import annotations

import sys
import time

import numpy as np

from ..tokenizer import ChatItem, EosDetector, EosResult, Sampler, TokenizerChatStops, chat_generator_for
from ..utils.seeds import fresh_seed
from .args import build_parser
from .runtime_setup import honor_cpu_platform_env, load_stack, log


def run_inference(args) -> None:
    config, params, tokenizer, engine = load_stack(args, n_lanes=1)
    prompt = args.prompt or "Hello"
    tokens = tokenizer.encode(prompt)
    log("📄", f"Prompt tokens: {len(tokens)}")
    if len(tokens) >= config.seq_len:
        # the reference asserts here (src/dllama.cpp eval loop); a clean
        # exit beats its abort — the API server truncates instead. Note
        # --max-seq-len only clamps DOWN, so it is not the remedy unless
        # the window was previously clamped below the model max.
        log("🚫", f"Prompt ({len(tokens)} tokens) does not fit the context "
            f"window ({config.seq_len}); shorten the prompt")
        if hasattr(engine, "stop_workers"):
            engine.stop_workers()  # release pod workers before exiting
        raise SystemExit(2)
    # one-shot inference keeps a FIXED no-seed default (12345: benchmark
    # runs stay reproducible without flags) — but `is not None`, not
    # `or`: an explicit --seed 0 is a real seed, not "no seed"
    sampler = Sampler(
        config.vocab_size, args.temperature, args.topp,
        args.seed if args.seed is not None else 12345,
    )

    t0 = time.perf_counter()
    logits, greedy, pos = engine.prefill(0, tokens)
    eval_s = time.perf_counter() - t0
    log("🔷", f"Eval {eval_s * 1000:8.2f} ms  ({len(tokens)} tokens, {len(tokens) / eval_s:.1f} tok/s)")

    cur = greedy if args.temperature == 0.0 else sampler.sample(np.asarray(logits))
    tokenizer.reset_decoder()
    out_pieces = []
    pred_times = []
    # per-token sync readout on a mesh (reference Sync ms + Sent/Recv kB,
    # src/dllama.cpp:54-64): payload bytes estimated from the compiled
    # decode program's collectives (parallel/comm_stats)
    sync_suffix = ""
    if args.benchmark and getattr(engine, "mesh", None) is not None:
        cstats = engine.collective_stats()
        if cstats.get("total_bytes"):
            sync_suffix = (
                f"  Sync {cstats['total_bytes'] / 1024:8.1f} kB/chip"
                f" ({cstats['n_collectives']} collectives)"
            )
    # prompt-lookup speculation for greedy runs (exact-stream identity; the
    # scheduler has the multi-lane version — SpecStream is the single-stream
    # one, shared with chat mode)
    from ..runtime.spec import SpecStream

    spec = SpecStream(
        engine,
        config,
        enabled=(
            args.temperature == 0.0 and not getattr(args, "no_spec", False)
        ),
        prompt_tokens=tokens,
        # greedy runs chain plain decode steps when no draft hits (one
        # dispatch per horizon); temp>0 samples from logits every step
        multi_h=(
            0 if args.temperature > 0.0
            else (8 if getattr(args, "multi_step", None) is None
                  else args.multi_step)
        ),
    )
    for _ in range(args.steps):
        piece = tokenizer.decode(cur)
        if piece:
            out_pieces.append(piece)
            print(piece, end="", flush=True)
        if tokenizer.is_eos(cur) or pos >= config.seq_len:
            break
        t1 = time.perf_counter()
        nxt, used_forward = spec.advance(cur, pos)
        if not used_forward:
            # cur's cache write already happened in the spec step
            pos += 1
            pred_times.append(0.0)  # token count for the tok/s summary
            cur = nxt
            continue
        if args.temperature > 0.0:
            nxt = sampler.sample(engine.lane_logits(spec.last_logits, 0))
        dt = time.perf_counter() - t1
        pred_times.append(dt)
        if args.benchmark:
            spec_note = f"  (spec +{len(spec.pending)})" if spec.pending else ""
            log("🔶", f"Pred {dt * 1000:8.2f} ms{sync_suffix}{spec_note}")
        pos += 1
        cur = nxt
    print()
    if pred_times:
        total = sum(pred_times)
        log("⏱", f"Evaluation: {eval_s * 1000:.2f} ms ({len(tokens) / eval_s:.2f} tok/s)")
        log("⏱", f"Prediction: {total * 1000:.2f} ms ({len(pred_times) / total:.2f} tok/s)")
    if args.benchmark and getattr(engine, "mesh", None) is not None:
        # measured split (profiler trace) next to the static byte estimate —
        # the reference's per-token Sync ms is a measured wall clock. Pod
        # roots return {} (RootControlEngine.measured_sync_stats: the probe
        # would deadlock workers), which the .get below skips.
        m = engine.measured_sync_stats()
        if m.get("sync_ms") is not None:
            log("⏱", f"Measured/step: {m['step_ms']:.2f} ms wall, "
                f"{m['device_busy_ms']:.2f} ms device, "
                f"Sync {m['sync_ms']:.2f} ms ({m['sync_frac'] * 100:.1f}% "
                f"of device, {m['source']})")
        elif m.get("step_ms") is not None:
            # the probe still measured wall time; the split needs a parsable
            # non-empty xplane trace (missing proto OR empty trace)
            log("⏱", f"Measured/step: {m['step_ms']:.2f} ms wall "
                "(sync split unavailable: empty or missing profiler trace)")
    if hasattr(engine, "stop_workers"):
        engine.stop_workers()


def run_chat(args) -> None:
    config, params, tokenizer, engine = load_stack(args, n_lanes=1)
    generator = chat_generator_for(tokenizer, args.chat_template)
    stops = TokenizerChatStops(tokenizer)
    # unseeded chats draw OS entropy (utils/seeds.py), not wall-clock
    # seconds: two sessions started in the same second must not replay
    # identical sampling streams. `is not None`, not `or`: an explicit
    # --seed 0 is a real (reproducible) seed, not "no seed"
    sampler = Sampler(
        config.vocab_size, args.temperature, args.topp,
        args.seed if args.seed is not None else fresh_seed(),
    )
    # greedy chat gets the same prompt-lookup speculation as inference mode
    # — the interactive path is where per-token latency is most visible,
    # and chat output (code, lists, repeated names) drafts well
    from ..runtime.spec import SpecStream

    spec = SpecStream(
        engine,
        config,
        enabled=(
            args.temperature == 0.0 and not getattr(args, "no_spec", False)
        ),
        multi_h=(
            0 if args.temperature > 0.0
            else (8 if getattr(args, "multi_step", None) is None
                  else args.multi_step)
        ),
    )

    pos = 0
    first = True
    print("💬 Chat mode. Ctrl-D to exit.")
    while True:
        try:
            user = input("\n> ")
        except EOFError:
            print()
            if hasattr(engine, "stop_workers"):
                engine.stop_workers()
            return
        items = []
        if first and args.prompt:
            items.append(ChatItem("system", args.prompt))
        items.append(ChatItem("user", user))
        chat = generator.generate(items, append_generation_prompt=True)
        first = False

        tokens = tokenizer.encode(chat.content, add_bos=(pos == 0))
        if pos + len(tokens) >= config.seq_len:
            log("🚫", "Context window full")
            return
        spec.extend_history(tokens)
        logits, greedy, pos = engine.prefill(0, tokens, start_pos=pos)
        cur = greedy if args.temperature == 0.0 else sampler.sample(np.asarray(logits))

        detector = EosDetector(tokenizer.eos_token_ids, stops.stops, 2, 2)
        decoder = tokenizer.make_stream_decoder()
        while pos < config.seq_len:
            piece = decoder.decode(cur)
            result = detector.append(cur, piece)
            if result == EosResult.EOS:
                delta = detector.get_delta()
                if delta:
                    print(delta, end="", flush=True)
                break
            if result == EosResult.NOT_EOS:
                delta = detector.get_delta()
                if delta:
                    print(delta, end="", flush=True)
                detector.reset()
            nxt, used_forward = spec.advance(cur, pos)
            if used_forward and args.temperature > 0.0:
                nxt = sampler.sample(engine.lane_logits(spec.last_logits, 0))
            pos += 1
            cur = nxt
        # spec lookahead past EOS is uncommitted cache scribble; the next
        # turn's prefill overwrites it from pos, so only the host-side
        # buffer needs clearing — discard_pending also RETRACTS the
        # partially consumed verify step from the acceptance counters, so
        # turn boundaries cannot skew the spec stats (the PR-9 leak fix)
        spec.discard_pending()
        print()


def run_worker(args) -> None:
    """Join the pod and replay root-broadcast engine calls until the root
    sends stop (reference: runWorkerApp, src/app.cpp:405-464).

    Launch (2 hosts):
      host0: dllama inference --coordinator host0:1234 --num-processes 2 \
                 --process-id 0 --workers tp8 --model m.m --tokenizer t.t ...
      host1: dllama worker    --coordinator host0:1234 --num-processes 2 \
                 --process-id 1 --workers tp8 --model m.m --tokenizer t.t
    Both hosts load the same model file; --workers describes the GLOBAL mesh.
    """
    import os

    from ..parallel.multihost import worker_serve

    if not (args.coordinator or os.environ.get("DLLAMA_COORDINATOR")):
        log("⭕", "Single process: no pod to join (pass --coordinator/--num-processes/--process-id).")
        log("⭕", "Single-host chips need no worker: shard with dllama inference --workers N ...")
        return
    config, params, tokenizer, engine = load_stack(args)
    plane = getattr(engine, "control_plane", None)
    assert plane is not None, "coordinator flags set but pod join failed"
    log("⭕", "Worker ready; replaying root engine calls")
    worker_serve(engine, plane, log=lambda m: log("⭕", m))
    log("⭕", "Root sent stop; worker exiting")


def run_train(args) -> None:
    """Next-token LM training on a text file — beyond parity (the
    reference is inference-only, src/app.cpp has no training path).
    Dense weights (training needs differentiable parameters, so Q40
    models load dequantized), optax AdamW, orbax checkpoints in
    --ckpt-dir with automatic resume from the latest step_<N>."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..formats.model_file import load_model_header
    from ..models.loader import load_params_from_m
    from ..tokenizer import Tokenizer
    from ..training import Trainer
    from .args import parse_mesh_spec

    if not (args.model and args.tokenizer):
        print("error: --model and --tokenizer are required", file=sys.stderr)
        raise SystemExit(2)
    if not args.data:
        print("error: train mode needs --data <utf-8 text file>", file=sys.stderr)
        raise SystemExit(2)
    h = load_model_header(args.model, max_seq_len=args.max_seq_len)
    config, params = load_params_from_m(args.model, h, dtype=jnp.float32)
    tokenizer = Tokenizer(args.tokenizer)

    with open(args.data, encoding="utf-8") as f:
        ids = tokenizer.encode(f.read())
    t_len = args.train_seq_len or config.seq_len
    if t_len > config.seq_len:
        # RoPE tables are seq_len rows; longer windows would silently
        # clamp-gather the last rotation for every position past seq_len
        print(
            f"error: --train-seq-len {t_len} exceeds the model's seq_len "
            f"{config.seq_len} (RoPE table size)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    n_win = len(ids) // t_len
    if n_win == 0:
        raise SystemExit(
            f"--data has {len(ids)} tokens; need at least one {t_len}-token window"
        )
    windows = np.asarray(ids[: n_win * t_len], np.int32).reshape(n_win, t_len)
    log("📄", f"Data: {len(ids)} tokens -> {n_win} windows of {t_len}")

    # same mesh-setup sequence as load_stack: validate the plan against the
    # model BEFORE sharding so bad --workers specs fail with a clear error,
    # and skip mesh setup entirely for a single device
    mesh = None
    plan = parse_mesh_spec(args.workers)
    if plan is not None and plan.n_devices > 1:
        from ..parallel import make_mesh, validate_mesh_for_config
        from ..parallel.sharding import shard_params

        validate_mesh_for_config(config, plan)
        mesh = make_mesh(plan)
        params = shard_params(params, mesh)
        log("🕸", f"Training over mesh {dict(mesh.shape)}")

    # LR schedule: linear warmup into cosine decay to 10% of peak over the
    # full run (--warmup-steps 0 keeps the flat --lr). The schedule count
    # lives in the optax state, so checkpoints resume it exactly.
    warmup = getattr(args, "warmup_steps", 0) or 0
    if warmup > 0:
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=args.lr,
            warmup_steps=warmup,
            decay_steps=max(args.train_steps, warmup + 1),
            end_value=args.lr * 0.1,
        )
    else:
        lr = args.lr
    trainer = Trainer(config, params, optax.adamw(lr), mesh=mesh)
    if args.ckpt_dir and Trainer.latest_step(args.ckpt_dir) is not None:
        trainer.restore(args.ckpt_dir)
        log("💾", f"Resumed from step {trainer.step_count} in {args.ckpt_dir}")

    # deterministic batch order: replay the skipped draws on resume so a
    # resumed run consumes the same batches a straight run would. An
    # explicit --seed (0 included — `or 0` used to collapse --seed 0 and
    # "no seed" into one stream) pins the order; the no-seed case draws
    # OS entropy through the sanctioned source (utils/seeds.fresh_seed,
    # dlint `replay-determinism`) and JOURNALS the draw in the
    # checkpoint dir (the admit-record rule, CLI edition), so an
    # unseeded run still resumes batch-for-batch from durable state
    import pathlib

    seed_file = (
        pathlib.Path(args.ckpt_dir) / "batch_seed" if args.ckpt_dir else None
    )
    journaled = (
        int(seed_file.read_text().strip())
        if seed_file is not None and seed_file.exists() else None
    )
    batch_seed = args.seed
    if batch_seed is None:
        if journaled is not None:
            batch_seed = journaled
            log("🎲", f"Batch-order seed (journaled): {batch_seed}")
        else:
            batch_seed = fresh_seed()
            log("🎲", f"Batch-order seed (drawn): {batch_seed}"
                + ("" if seed_file is not None
                   else " — pass --seed to reproduce"))
    elif journaled is not None and journaled != batch_seed:
        # explicit --seed wins, but silently diverging from the stream
        # that produced the existing checkpoints is exactly the hazard
        # the journal exists to prevent — say so
        log("⚠️", f"--seed {batch_seed} overrides the journaled "
            f"batch-order seed {journaled}: resumed batches will NOT "
            "match the run that wrote these checkpoints")
    # ALWAYS journal the resolved seed (explicitly seeded runs included):
    # a later `--ckpt-dir`-only resume must replay the same stream
    if seed_file is not None and journaled != batch_seed:
        seed_file.parent.mkdir(parents=True, exist_ok=True)
        seed_file.write_text(f"{batch_seed}\n")
    rng = np.random.default_rng(batch_seed)
    for _ in range(trainer.step_count):
        rng.integers(0, n_win, size=args.batch_size)

    tokens_per_step = args.batch_size * t_len
    last_saved = None
    while trainer.step_count < args.train_steps:
        idx = rng.integers(0, n_win, size=args.batch_size)
        t0 = time.perf_counter()
        loss = trainer.step(windows[idx])
        dt = time.perf_counter() - t0
        log("📉", f"step {trainer.step_count:5d}  loss {loss:8.4f}  "
            f"{tokens_per_step / dt:8.1f} tok/s")
        if (
            args.ckpt_dir
            and args.save_every > 0
            and trainer.step_count % args.save_every == 0
        ):
            log("💾", f"Checkpoint: {trainer.save(args.ckpt_dir)}")
            last_saved = trainer.step_count
    if args.ckpt_dir and last_saved != trainer.step_count:
        log("💾", f"Final checkpoint: {trainer.save(args.ckpt_dir)}")


def main(argv=None) -> None:
    honor_cpu_platform_env()
    args = build_parser("dllama").parse_args(argv)
    if args.mode == "inference":
        run_inference(args)
    elif args.mode == "chat":
        run_chat(args)
    elif args.mode == "worker":
        run_worker(args)
    elif args.mode == "train":
        run_train(args)


if __name__ == "__main__":
    main()
