#!/bin/sh
# Install the repo's git pre-commit hook: the diff-proportional dlint
# run (`dlint --changed HEAD`, shipped in PR 14 — docs/LINT.md "Linting
# just the diff"). Findings surface at commit time instead of in
# tier-1; `git commit --no-verify` stays the escape hatch.
#
# Idempotent: re-running refreshes a hook this script installed (the
# marker line below identifies it) and REFUSES to clobber any other
# pre-commit hook — chain dlint from your own hook instead.
#
# Usage: scripts/install_hooks.sh   (or `make hooks`)
set -eu

MARKER="# dlint-pre-commit-hook"

repo_root=$(git rev-parse --show-toplevel 2>/dev/null) || {
    echo "install_hooks.sh: not inside a git work tree" >&2
    exit 1
}
# honor core.hooksPath when set (defaults to .git/hooks)
hooks_dir=$(git -C "$repo_root" rev-parse --git-path hooks)
case "$hooks_dir" in
    /*) : ;;
    *) hooks_dir="$repo_root/$hooks_dir" ;;
esac
hook="$hooks_dir/pre-commit"

if [ -e "$hook" ] && ! grep -q "$MARKER" "$hook" 2>/dev/null; then
    echo "install_hooks.sh: $hook exists and was not installed by this" >&2
    echo "script — not clobbering it. Add this line to your hook instead:" >&2
    echo "  python -m distributed_llama_multiusers_tpu.analysis --changed HEAD" >&2
    exit 1
fi

mkdir -p "$hooks_dir"
cat > "$hook" <<EOF
#!/bin/sh
$MARKER
# Diff-proportional project-invariant lint (docs/LINT.md): only files
# changed vs HEAD are checked, but every file still feeds the
# cross-file models (locks, protocol surface, jit surface), so a
# violation against an unchanged declaration is still found.
# Bypass for a single commit with: git commit --no-verify
exec python -m distributed_llama_multiusers_tpu.analysis --changed HEAD
EOF
chmod +x "$hook"
echo "installed $hook (dlint --changed HEAD; bypass: git commit --no-verify)"
