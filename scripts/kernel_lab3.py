"""Kernel lab 3: cheaper-dequant Q40 matmul variants, measured on real TPU.

Round-5 finding (BENCH_LIVE primary + 8b phases): hbm_util is ~0.26 for the
1B AND ~0.24 for the 8B — a per-BYTE cost, not per-launch. The dequant chain
costs ~4.5 VPU ops/weight (int32 unpack relayout, mask/shift, int->f32 cast,
f32 scale mul, f32->bf16 cast); at the VPU's ~1e12 ops/s that alone accounts
for the entire observed decode time — DMA hides under it. These variants cut
per-weight VPU work:

  full_v4         current product chain (baseline: f32 dequant -> bf16 cast)
  full_bf16chain  dequant in bf16 end-to-end: nib int32->bf16, bf16 scale mul
                  (drops the f32 round-trip: ~1 op/weight less)
  full_repeat     bf16 chain + scale broadcast via pltpu.repeat instead of
                  the reshape(n_blk,16,t)*s3 reshape dance (relayout suspect)
  full_blockdot   per-quant-block MXU dots on raw bf16 nibbles; the scale is
                  applied to each block's [m,t] OUTPUT (m/32 ops per weight
                  instead of 1): per-weight VPU = mask + cast only
  full_u8nib      nibble extraction on native 8-bit lanes (mask before the
                  int32 relayout), then one int8->bf16 cast

XLA-level (no Pallas) int4-resident alternatives:
  xla_int4_raw    y = x @ W4.astype(bf16) — XLA's own int4 read+convert+dot
  xla_int4_scaled same with the per-block scale woven in pre-dot

Run on TPU:  python scripts/kernel_lab3.py [d_in] [d_out] [L] [reps]
Correctness: python scripts/kernel_lab3.py --check   (interpret mode, CPU)
Adopt:       python scripts/kernel_lab3.py [shape...] --adopt

--adopt makes the lab adopt-and-verify: after timing, the fastest product
variant is re-verified against the numpy oracle (the --check gate) and
then recorded into ops/dequant_table.json as a per-(d_in, d_out) decode
row for DLLAMA_DEQUANT=auto to pick up at the next serving start.
"""

from __future__ import annotations

import sys
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.6 spells pltpu.CompilerParams "TPUCompilerParams" (same kwargs)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

sys.path.insert(0, ".")

from distributed_llama_multiusers_tpu.ops.pallas_q40 import (  # noqa: E402
    _f16_bits_to_f32,
)

HBM_GB_S = 819.0  # v5e
M = 8
CHUNK = 2048  # d_in per grid step
TILE = 512  # d_out per grid step
_REPS = 8
_INTERPRET = False


# ---------------------------------------------------------------------------
# kernel bodies. Shared operand layout (all pre-split outside the kernel,
# matching the product kernel's convention):
#   xl/xh  [M, half]        block-local nibble halves of x's columns
#   xlt/xht[half, M]        the same, transposed (blockdot wants sublane
#                           slicing at 16-row granularity)
#   bsum_t [n_blk, M]       per-quant-block x sums, transposed
#   p      [half, d_out]    packed nibbles
#   s      [n_blk, d_out]   f16 scale bits (int16)
# ---------------------------------------------------------------------------


def _k_v4(t_ref, xl_ref, xh_ref, bs_ref, p_ref, s_ref, o_ref):
    """Current product chain: f32 dequant, bf16 dot operands."""
    rows, tile = p_ref.shape
    n_blk = rows // 16
    p = p_ref[...].astype(jnp.int32)
    s = _f16_bits_to_f32(s_ref[...])
    s3 = s[:, None, :]
    w_lo = ((p & 0x0F).astype(jnp.float32).reshape(n_blk, 16, tile) * s3)
    w_hi = ((p >> 4).astype(jnp.float32).reshape(n_blk, 16, tile) * s3)
    w_lo = w_lo.reshape(rows, tile).astype(jnp.bfloat16)
    w_hi = w_hi.reshape(rows, tile).astype(jnp.bfloat16)
    corr = jax.lax.dot_general(
        bs_ref[...], s, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = (
        jnp.dot(xl_ref[...].astype(jnp.bfloat16), w_lo,
                preferred_element_type=jnp.float32)
        + jnp.dot(xh_ref[...].astype(jnp.bfloat16), w_hi,
                  preferred_element_type=jnp.float32)
        - 8.0 * corr + t_ref[0, 0]
    )


def _k_bf16chain(t_ref, xl_ref, xh_ref, bs_ref, p_ref, s_ref, o_ref):
    """Dequant entirely in bf16: int32 nibbles cast straight to bf16 (exact:
    0..15), scales decoded once to bf16 (amortized /32), one bf16 mul."""
    rows, tile = p_ref.shape
    n_blk = rows // 16
    p = p_ref[...].astype(jnp.int32)
    s_f32 = _f16_bits_to_f32(s_ref[...])
    s_bf = s_f32.astype(jnp.bfloat16)[:, None, :]
    w_lo = ((p & 0x0F).astype(jnp.bfloat16).reshape(n_blk, 16, tile) * s_bf)
    w_hi = ((p >> 4).astype(jnp.bfloat16).reshape(n_blk, 16, tile) * s_bf)
    w_lo = w_lo.reshape(rows, tile)
    w_hi = w_hi.reshape(rows, tile)
    corr = jax.lax.dot_general(
        bs_ref[...], s_f32, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = (
        jnp.dot(xl_ref[...].astype(jnp.bfloat16), w_lo,
                preferred_element_type=jnp.float32)
        + jnp.dot(xh_ref[...].astype(jnp.bfloat16), w_hi,
                  preferred_element_type=jnp.float32)
        - 8.0 * corr + t_ref[0, 0]
    )


def _k_repeat(t_ref, xl_ref, xh_ref, bs_ref, p_ref, s_ref, o_ref):
    """bf16 chain, scale broadcast via jnp.repeat (no reshape dance).
    (pltpu.repeat TILES the array — s0..sB,s0..sB — which is the wrong
    order for the block-contiguous packed layout; jnp.repeat keeps each
    block's 16 rows consecutive.)"""
    rows, tile = p_ref.shape
    p = p_ref[...].astype(jnp.int32)
    s_f32 = _f16_bits_to_f32(s_ref[...])
    s_rep = jnp.repeat(s_f32.astype(jnp.bfloat16), 16, axis=0)  # [rows, tile]
    w_lo = (p & 0x0F).astype(jnp.bfloat16) * s_rep
    w_hi = (p >> 4).astype(jnp.bfloat16) * s_rep
    corr = jax.lax.dot_general(
        bs_ref[...], s_f32, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = (
        jnp.dot(xl_ref[...].astype(jnp.bfloat16), w_lo,
                preferred_element_type=jnp.float32)
        + jnp.dot(xh_ref[...].astype(jnp.bfloat16), w_hi,
                  preferred_element_type=jnp.float32)
        - 8.0 * corr + t_ref[0, 0]
    )


def _k_blockdot(t_ref, xlt_ref, xht_ref, bs_ref, p_ref, s_ref, o_ref):
    """Per-block MXU dots on RAW nibbles; scales hit each block's [M, tile]
    output: per-weight VPU work = mask + int->bf16 cast only. The -8 offset
    folds into the same post-scale FMA via the per-block x sums."""
    rows, tile = p_ref.shape
    n_blk = rows // 16
    p = p_ref[...].astype(jnp.int32)
    nib_lo = (p & 0x0F).astype(jnp.bfloat16)  # [rows, tile]
    nib_hi = (p >> 4).astype(jnp.bfloat16)
    s = _f16_bits_to_f32(s_ref[...])  # [n_blk, tile] f32
    bs = bs_ref[...]  # [n_blk, M]
    acc = jnp.zeros_like(o_ref)
    dn = (((0,), (0,)), ((), ()))
    for b in range(n_blk):
        lo = jax.lax.dot_general(
            xlt_ref[16 * b:16 * (b + 1), :].astype(jnp.bfloat16),
            nib_lo[16 * b:16 * (b + 1), :], dn,
            preferred_element_type=jnp.float32,
        )
        hi = jax.lax.dot_general(
            xht_ref[16 * b:16 * (b + 1), :].astype(jnp.bfloat16),
            nib_hi[16 * b:16 * (b + 1), :], dn,
            preferred_element_type=jnp.float32,
        )
        acc = acc + (lo + hi - 8.0 * bs[b, :, None]) * s[b][None, :]
    o_ref[...] = acc + t_ref[0, 0]


def _k_i8blockdot(t_ref, xlt_ref, xht_ref, aux_ref, p_ref, s_ref, o_ref):
    """Q80-style int8 MXU dots: the raw nibbles (int8, no cast, no scale)
    feed the MXU directly; activations arrive pre-quantized to int8 per
    quant block (xq = round(x / sx), the reference's own activation
    format). Per block b:

        y += s_b * (sx[:,b,None] * (xq_lo_b @ nib_lo_b + xq_hi_b @ nib_hi_b)
                    - 8 * bsum_b)

    Per-weight VPU work = the 8-bit-lane mask ONLY (~0.5-1 op); the
    rescale costs ~4*m/32 ops/weight. aux packs bsum and sx interleaved
    on the sublane axis: aux[2b] = bsum[b], aux[2b+1] = sx[b]."""
    rows, tile = p_ref.shape
    n_blk = rows // 16
    aux = aux_ref[...].reshape(n_blk, 2, M)
    bs = aux[:, 0, :]  # [n_blk, M] f32
    sx = aux[:, 1, :]  # [n_blk, M] f32
    p8 = p_ref[...]
    nib_lo = (p8 & jnp.uint8(0x0F)).astype(jnp.int8)
    nib_hi = (p8 >> jnp.uint8(4)).astype(jnp.int8)
    s = _f16_bits_to_f32(s_ref[...])  # [n_blk, tile]
    xl = xlt_ref[...]  # [rows, M] int8
    xh = xht_ref[...]
    dn = (((0,), (0,)), ((), ()))
    acc = None
    for b in range(n_blk):
        lo = jax.lax.dot_general(
            xl[16 * b:16 * (b + 1), :], nib_lo[16 * b:16 * (b + 1), :], dn,
            preferred_element_type=jnp.int32,
        )
        hi = jax.lax.dot_general(
            xh[16 * b:16 * (b + 1), :], nib_hi[16 * b:16 * (b + 1), :], dn,
            preferred_element_type=jnp.int32,
        )
        d = (lo + hi).astype(jnp.float32)  # [M, tile]
        contrib = (sx[b][:, None] * d - 8.0 * bs[b][:, None]) * s[b][None, :]
        acc = contrib if acc is None else acc + contrib
    o_ref[...] = acc + t_ref[0, 0]


def _quantize_x_blocks(xf, d_in):
    """Reference-Q80-style per-block activation quantization for the
    i8blockdot operands: returns (xq_lo_T, xq_hi_T int8 [half, M],
    aux f32 [n_blk*2, M] with bsum/sx interleaved)."""
    m = xf.shape[0]
    n_blk = d_in // 32
    xb = np.asarray(xf, np.float32).reshape(m, n_blk, 32)
    sx = np.abs(xb).max(axis=2) / 127.0  # [m, n_blk]
    sx = np.where(sx == 0, 1e-8, sx)
    xq = np.clip(np.rint(xb / sx[:, :, None]), -127, 127).astype(np.int8)
    bsum = xb.sum(axis=2)  # [m, n_blk] (EXACT x sums for the -8 fold)
    xq_lo = xq[:, :, :16].reshape(m, d_in // 2)
    xq_hi = xq[:, :, 16:].reshape(m, d_in // 2)
    aux = np.empty((n_blk * 2, m), np.float32)
    aux[0::2] = bsum.T
    aux[1::2] = sx.T
    return (
        jnp.asarray(xq_lo.T), jnp.asarray(xq_hi.T), jnp.asarray(aux)
    )


def _k_u8nib(t_ref, xl_ref, xh_ref, bs_ref, p_ref, s_ref, o_ref):
    """Mask on native 8-bit lanes BEFORE any widening, then int8->bf16."""
    rows, tile = p_ref.shape
    n_blk = rows // 16
    p8 = p_ref[...]
    lo8 = (p8 & jnp.uint8(0x0F)).astype(jnp.int8)
    hi8 = (p8 >> jnp.uint8(4)).astype(jnp.int8)
    s_f32 = _f16_bits_to_f32(s_ref[...])
    s_bf = s_f32.astype(jnp.bfloat16)[:, None, :]
    w_lo = (lo8.astype(jnp.bfloat16).reshape(n_blk, 16, tile) * s_bf)
    w_hi = (hi8.astype(jnp.bfloat16).reshape(n_blk, 16, tile) * s_bf)
    w_lo = w_lo.reshape(rows, tile)
    w_hi = w_hi.reshape(rows, tile)
    corr = jax.lax.dot_general(
        bs_ref[...], s_f32, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = (
        jnp.dot(xl_ref[...].astype(jnp.bfloat16), w_lo,
                preferred_element_type=jnp.float32)
        + jnp.dot(xh_ref[...].astype(jnp.bfloat16), w_hi,
                  preferred_element_type=jnp.float32)
        - 8.0 * corr + t_ref[0, 0]
    )


KERNELS = {
    "full_v4": (_k_v4, False),
    "full_bf16chain": (_k_bf16chain, False),
    "full_repeat": (_k_repeat, False),
    "full_blockdot": (_k_blockdot, True),  # True: wants transposed x
    "full_u8nib": (_k_u8nib, False),
}
# i8blockdot is special-cased (int8 x operands + interleaved bsum/sx aux)

# lab variant -> shipping DEQUANT_MODES name, for --adopt (the XLA int4
# probes have no product counterpart and are never adopted)
ADOPT_MODES = {
    "full_v4": "v4",
    "full_bf16chain": "bf16chain",
    "full_repeat": "repeat",
    "full_u8nib": "u8chain",
    "full_blockdot": "blockdot",
    "full_i8blockdot": "i8blockdot",
}


def _call_i8blockdot(xf, packed, sbits, d_in, d_out, chunk, tile):
    half = d_in // 2
    xq_lo, xq_hi, aux = _quantize_x_blocks(np.asarray(xf), d_in)
    t = jnp.zeros((1, 128), jnp.float32)
    return pl.pallas_call(
        lambda t_ref, a, b, c, p_, s_, o: _k_i8blockdot(t_ref, a, b, c, p_, s_, o),
        grid=(d_out // tile, half // (chunk // 2)),
        in_specs=[
            pl.BlockSpec((1, 128), lambda j, k: (0, 0)),
            pl.BlockSpec((chunk // 2, M), lambda j, k: (k, 0)),
            pl.BlockSpec((chunk // 2, M), lambda j, k: (k, 0)),
            pl.BlockSpec(((chunk // 32) * 2, M), lambda j, k: (k, 0)),
            pl.BlockSpec((chunk // 2, tile), lambda j, k: (k, j)),
            pl.BlockSpec((chunk // 32, tile), lambda j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((M, tile), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((M, d_out), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
    )(t, xq_lo, xq_hi, aux, packed, sbits)


def _ref_dequant(packed, scales):
    """numpy oracle: dense f32 weights from one packed plane."""
    half, d_out = packed.shape
    n_blk = half // 16
    p = np.asarray(packed).astype(np.int32)
    s = np.asarray(scales).astype(np.float32)  # [n_blk, d_out]
    lo = (p & 0x0F).reshape(n_blk, 16, d_out)
    hi = (p >> 4).reshape(n_blk, 16, d_out)
    w = np.zeros((half * 2, d_out), np.float32)
    wb = w.reshape(n_blk, 32, d_out)
    wb[:, :16] = (lo - 8) * s[:, None, :]
    wb[:, 16:] = (hi - 8) * s[:, None, :]
    return w


def _split_x(xf, d_in):
    m = xf.shape[0]
    half = d_in // 2
    xb = xf.reshape(m, d_in // 32, 2, 16)
    x_lo = xb[:, :, 0, :].reshape(m, half)
    x_hi = xb[:, :, 1, :].reshape(m, half)
    bsum_t = xf.reshape(m, d_in // 32, 32).sum(axis=2).T
    return x_lo, x_hi, bsum_t


def _call_kernel(name, xf, packed, sbits, d_in, d_out, chunk, tile):
    """One full-plane matmul through variant `name` (single-plane grid)."""
    kern, transposed = KERNELS[name]
    half = d_in // 2
    x_lo, x_hi, bsum_t = _split_x(xf, d_in)
    if transposed:
        xa, xb_ = x_lo.T, x_hi.T
        x_spec = pl.BlockSpec((chunk // 2, M), lambda j, k: (k, 0))
    else:
        xa, xb_ = x_lo, x_hi
        x_spec = pl.BlockSpec((M, chunk // 2), lambda j, k: (0, k))
    t = jnp.zeros((1, 128), jnp.float32)
    return pl.pallas_call(
        lambda t_ref, a, b, c, p_, s_, o: kern(t_ref, a, b, c, p_, s_, o),
        grid=(d_out // tile, half // (chunk // 2)),
        in_specs=[
            pl.BlockSpec((1, 128), lambda j, k: (0, 0)),
            x_spec,
            x_spec,
            pl.BlockSpec((chunk // 32, M), lambda j, k: (k, 0)),
            pl.BlockSpec((chunk // 2, tile), lambda j, k: (k, j)),
            pl.BlockSpec((chunk // 32, tile), lambda j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((M, tile), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((M, d_out), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
    )(t, xa, xb_, bsum_t, packed, sbits)


def check():
    """Interpret-mode correctness: every variant vs the numpy oracle.

    NOTE: accumulation over the d_in grid axis relies on out_ref revisiting
    (arbitrary k axis) — in this lab the k axis ADDs t_ref noise per step, so
    for the check we use a single-chunk plane (d_in == chunk). Small shapes:
    interpret mode emulates the blockdot's unrolled per-block dots slowly."""
    global _INTERPRET
    _INTERPRET = True
    chunk, tile = 512, 256
    d_in, d_out = chunk, tile * 2
    rng = np.random.default_rng(0)
    packed = jnp.asarray(rng.integers(0, 256, (d_in // 2, d_out), np.uint8))
    scales = (rng.random((d_in // 32, d_out), np.float32) * 0.01 + 1e-3)
    sb = jax.lax.bitcast_convert_type(
        jnp.asarray(scales, jnp.float32).astype(jnp.float16), jnp.int16
    )
    xf = jnp.asarray(rng.standard_normal((M, d_in), np.float32))
    w_ref = _ref_dequant(packed, np.asarray(scales, np.float32).astype(np.float16))
    y_ref = np.asarray(xf) @ w_ref
    failed = False
    for name in KERNELS:
        y = np.asarray(
            _call_kernel(name, xf, packed, sb, d_in, d_out, chunk, tile)
        )
        rel = np.abs(y - y_ref).max() / (np.abs(y_ref).max() + 1e-9)
        ok = rel < 2e-2
        failed |= not ok
        print(f"{name:16s} max-rel-err {rel:.2e}  {'ok' if ok else 'FAIL'}")
    # i8blockdot quantizes the ACTIVATIONS too (reference Q80 semantics) —
    # looser bound than the weight-only variants
    y = np.asarray(_call_i8blockdot(xf, packed, sb, d_in, d_out, chunk, tile))
    rel = np.abs(y - y_ref).max() / (np.abs(y_ref).max() + 1e-9)
    ok = rel < 5e-2
    failed |= not ok
    print(f"{'full_i8blockdot':16s} max-rel-err {rel:.2e}  {'ok' if ok else 'FAIL'}")
    if failed:
        sys.exit(1)


def main():
    if "--check" in sys.argv:
        check()
        return
    adopt = "--adopt" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    d_in = int(args[0]) if len(args) > 0 else 4096
    d_out = int(args[1]) if len(args) > 1 else 14336
    L = int(args[2]) if len(args) > 2 else 8
    global _REPS
    _REPS = int(args[3]) if len(args) > 3 else 8
    half = d_in // 2
    n_blk_all = d_in // 32

    kp, ks, kx = jax.random.split(jax.random.PRNGKey(0), 3)
    packed = jax.random.bits(kp, (L, half, d_out), jnp.uint8)
    scales = (
        jax.random.uniform(ks, (L, n_blk_all, d_out), jnp.float32) * 0.01
        + 0.001
    ).astype(jnp.float16)
    sbits = jax.lax.bitcast_convert_type(scales, jnp.int16)
    xf = jax.random.normal(kx, (M, d_in), jnp.float32)
    x_lo, x_hi, bsum_t = _split_x(xf, d_in)
    jax.block_until_ready((packed, sbits, x_lo))
    pbytes = packed.size
    print(f"d_in={d_in} d_out={d_out} L={L} M={M} packed={pbytes/1e6:.1f} MB "
          f"device={jax.devices()[0].device_kind}", flush=True)

    grid = (L, d_out // TILE, half // (CHUNK // 2))
    t_spec = pl.BlockSpec((1, 128), lambda l, j, k: (0, 0))
    p_spec = pl.BlockSpec((1, CHUNK // 2, TILE), lambda l, j, k: (l, k, j))
    s_spec = pl.BlockSpec((1, CHUNK // 32, TILE), lambda l, j, k: (l, k, j))
    o_spec = pl.BlockSpec((M, TILE), lambda l, j, k: (0, j))
    o_shape = jax.ShapeDtypeStruct((M, d_out), jnp.float32)
    params = _CompilerParams(
        dimension_semantics=("arbitrary", "parallel", "arbitrary"),
    )

    times: dict = {}
    for name, (kern, transposed) in KERNELS.items():
        if transposed:
            xa, xb_ = x_lo.T, x_hi.T
            x_spec = pl.BlockSpec((CHUNK // 2, M), lambda l, j, k: (k, 0))
        else:
            xa, xb_ = x_lo, x_hi
            x_spec = pl.BlockSpec((M, CHUNK // 2), lambda l, j, k: (0, k))
        bs_spec = pl.BlockSpec((CHUNK // 32, M), lambda l, j, k: (k, 0))

        def call(t, kern=kern, xa=xa, xb_=xb_, x_spec=x_spec, bs_spec=bs_spec):
            def wrapped(t_ref, xa_ref, xb_ref, bs_ref, p_ref, s_ref, o_ref):
                kern(t_ref, xa_ref, xb_ref, bs_ref, p_ref.at[0], s_ref.at[0],
                     o_ref)

            return pl.pallas_call(
                wrapped, grid=grid,
                in_specs=[t_spec, x_spec, x_spec, bs_spec, p_spec, s_spec],
                out_specs=o_spec, out_shape=o_shape,
                compiler_params=params,
            )(t, xa, xb_, bsum_t, packed, sbits)

        times[name] = timeit(name, call, pbytes)

    # ---- i8blockdot: int8 MXU dots on Q80-quantized activations -----------
    xq_lo, xq_hi, aux = _quantize_x_blocks(np.asarray(xf), d_in)
    jax.block_until_ready((xq_lo, xq_hi, aux))
    xi8_spec = pl.BlockSpec((CHUNK // 2, M), lambda l, j, k: (k, 0))
    aux_spec = pl.BlockSpec(((CHUNK // 32) * 2, M), lambda l, j, k: (k, 0))

    def call_i8(t):
        def wrapped(t_ref, a, b, c, p_ref, s_ref, o_ref):
            _k_i8blockdot(t_ref, a, b, c, p_ref.at[0], s_ref.at[0], o_ref)

        return pl.pallas_call(
            wrapped, grid=grid,
            in_specs=[t_spec, xi8_spec, xi8_spec, aux_spec, p_spec, s_spec],
            out_specs=o_spec, out_shape=o_shape,
            compiler_params=params,
        )(t, xq_lo, xq_hi, aux, packed, sbits)

    times["full_i8blockdot"] = timeit("full_i8blockdot", call_i8, pbytes)

    # ---- XLA-level int4 alternatives (no Pallas) --------------------------
    try:
        w4 = jax.random.randint(
            jax.random.PRNGKey(7), (L, d_in, d_out), -8, 8, jnp.int8
        ).astype(jnp.int4)
        s_bf = scales.astype(jnp.bfloat16)
        jax.block_until_ready(w4)
        i4bytes = w4.size // 2  # int4 packs 2/byte in HBM

        def raw(t):
            def body(_, acc):
                y = None
                for i in range(L):
                    yi = jnp.matmul(
                        xf.astype(jnp.bfloat16) + acc.astype(jnp.bfloat16),
                        w4[i].astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32,
                    )
                    y = yi if y is None else y + yi
                return y.reshape(-1)[0] * 1e-30
            return jax.lax.fori_loop(0, 1, body, t)

        timeit_xla("xla_int4_raw", raw, i4bytes)

        def scaled(t):
            def body(_, acc):
                y = None
                for i in range(L):
                    wd = (
                        w4[i].astype(jnp.bfloat16).reshape(n_blk_all, 32, d_out)
                        * s_bf[i][:, None, :]
                    ).reshape(d_in, d_out)
                    yi = jnp.matmul(
                        xf.astype(jnp.bfloat16) + acc.astype(jnp.bfloat16),
                        wd, preferred_element_type=jnp.float32,
                    )
                    y = yi if y is None else y + yi
                return y.reshape(-1)[0] * 1e-30
            return jax.lax.fori_loop(0, 1, body, t)

        timeit_xla("xla_int4_scaled", scaled, i4bytes)
    except Exception as e:  # noqa: BLE001
        print(f"xla_int4: unavailable ({type(e).__name__}: {str(e)[:120]})")

    if adopt:
        _adopt(times, d_in, d_out)


def _adopt(times, d_in, d_out):
    """--adopt: verify the fastest product variant against the numpy
    oracle (the --check gate; exits non-zero on parity failure), then
    record it into the persisted selection table as a per-(d_in, d_out)
    decode row (M=8 here is squarely decode-class)."""
    timed = {ADOPT_MODES[n]: t for n, t in times.items()
             if t is not None and n in ADOPT_MODES}
    if not timed:
        print("ADOPT: no product variant timed; nothing recorded")
        return
    mode = min(timed, key=timed.get)
    print(f"ADOPT: fastest product variant = {mode} "
          f"({timed[mode] * 1e3:.3f} ms/pass); verifying before recording")
    check()
    from distributed_llama_multiusers_tpu.ops.dequant_select import record_win

    path = record_win(
        d_in, d_out, "decode", mode,
        source=f"scripts/kernel_lab3.py --adopt "
               f"({timed[mode] * 1e3:.3f} ms/pass, M={M})",
    )
    print(f"TABLE: {d_in}x{d_out}/decode -> {mode} recorded in {path}")


def timeit(name, build_call, bytes_per_pass, reps=None):
    reps = reps if reps is not None else _REPS

    @jax.jit
    def loop(seed):
        def body(_, acc):
            t = jnp.full((1, 128), acc, jnp.float32)
            out = build_call(t)
            return out.reshape(-1)[0].astype(jnp.float32) * 1e-30
        return jax.lax.fori_loop(0, reps, body, seed)

    return _report(name, loop, bytes_per_pass, reps)


def timeit_xla(name, fn, bytes_per_pass, reps=None):
    reps = reps if reps is not None else _REPS

    @jax.jit
    def loop(seed):
        def body(_, acc):
            return fn(acc)
        return jax.lax.fori_loop(0, reps, body, seed)

    return _report(name, loop, bytes_per_pass, reps)


def _report(name, loop, bytes_per_pass, reps):
    try:
        np.asarray(loop(jnp.float32(0)))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(loop(jnp.float32(0)))
            best = min(best, time.perf_counter() - t0)
        sec = best / reps
        gbs = bytes_per_pass / sec / 1e9
        print(f"{name:16s} {sec * 1e3:8.3f} ms  {gbs:7.1f} GB/s "
              f"({gbs / HBM_GB_S * 100:5.1f}% HBM)", flush=True)
        return sec
    except Exception as e:  # noqa: BLE001
        print(f"{name:16s} FAILED: {type(e).__name__}: {str(e)[:140]}",
              flush=True)
        return None


if __name__ == "__main__":
    main()
