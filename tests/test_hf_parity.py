"""True HuggingFace parity: tiny checkpoints run through convert-hf.py at
f32 (no quantization) must produce the same logits as `transformers`' own
forward on the same weights.

This is a stronger bar than the numpy-oracle tests (which share this
repo's RoPE/attention code): transformers is an independent
implementation, so agreement here pins the converter's tensor ordering,
the q/k rotary permutation (HF half-rotation -> interleaved), the GQA
attention semantics, and — for Qwen2 — the bias handling, against the
ecosystem reference the checkpoints actually come from.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

_CONVERTER_DIR = os.path.join(os.path.dirname(__file__), "..", "converter")


def _load_converter():
    path = os.path.join(_CONVERTER_DIR, "convert-hf.py")
    sys.path.insert(0, _CONVERTER_DIR)
    spec = importlib.util.spec_from_file_location("convert_hf_parity", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _tiny_cfg(model_type: str) -> dict:
    return {
        "model_type": model_type,
        "architectures": [
            "Qwen2ForCausalLM" if model_type == "qwen2" else "LlamaForCausalLM"
        ],
        "hidden_act": "silu",
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "max_position_embeddings": 64,
        "vocab_size": 96,
        "rope_theta": 10000.0,
        # match the runtime's fixed norm epsilon (the .m header carries no
        # eps key; both the reference and this framework pin 1e-5)
        "rms_norm_eps": 1e-5,
        "tie_word_embeddings": False,
        "torch_dtype": "float32",
    }


def _write_checkpoint(d, cfg, with_bias: bool):
    torch = pytest.importorskip("torch")
    from safetensors.torch import save_file

    dim, hidden = cfg["hidden_size"], cfg["intermediate_size"]
    heads, kv = cfg["num_attention_heads"], cfg["num_key_value_heads"]
    kv_dim = dim * kv // heads
    vocab = cfg["vocab_size"]
    g = torch.Generator().manual_seed(7)

    def r(*shape):
        return torch.randn(*shape, generator=g) * 0.05

    tensors = {"model.embed_tokens.weight": r(vocab, dim)}
    for l in range(cfg["num_hidden_layers"]):
        p = f"model.layers.{l}"
        tensors[f"{p}.self_attn.q_proj.weight"] = r(dim, dim)
        tensors[f"{p}.self_attn.k_proj.weight"] = r(kv_dim, dim)
        tensors[f"{p}.self_attn.v_proj.weight"] = r(kv_dim, dim)
        tensors[f"{p}.self_attn.o_proj.weight"] = r(dim, dim)
        if with_bias:
            tensors[f"{p}.self_attn.q_proj.bias"] = r(dim)
            tensors[f"{p}.self_attn.k_proj.bias"] = r(kv_dim)
            tensors[f"{p}.self_attn.v_proj.bias"] = r(kv_dim)
        tensors[f"{p}.mlp.gate_proj.weight"] = r(hidden, dim)
        tensors[f"{p}.mlp.down_proj.weight"] = r(dim, hidden)
        tensors[f"{p}.mlp.up_proj.weight"] = r(hidden, dim)
        tensors[f"{p}.input_layernorm.weight"] = 1.0 + 0.1 * r(dim)
        tensors[f"{p}.post_attention_layernorm.weight"] = 1.0 + 0.1 * r(dim)
    tensors["model.norm.weight"] = 1.0 + 0.1 * r(dim)
    tensors["lm_head.weight"] = r(vocab, dim)

    (d / "config.json").write_text(json.dumps(cfg))
    save_file(tensors, str(d / "model.safetensors"))


def _hf_logits(folder: str, tokens: list[int]) -> np.ndarray:
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    model = transformers.AutoModelForCausalLM.from_pretrained(
        folder, dtype=torch.float32
    )
    model.eval()
    with torch.no_grad():
        out = model(torch.tensor([tokens]), use_cache=False)
    return out.logits[0].float().numpy()  # [T, vocab]


def _ours_logits(m_path: str, tokens: list[int]) -> np.ndarray:
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.formats import load_model_header
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        load_params_from_m,
    )

    h = load_model_header(m_path)
    config, params = load_params_from_m(m_path, h, dtype=jnp.float32)
    toks = jnp.array([tokens], jnp.int32)
    poss = jnp.arange(len(tokens), dtype=jnp.int32)[None, :]
    logits, _ = llama_forward(
        config, params, toks, poss, init_kv_cache(config, 1),
        emulate_q80_activations=False,
    )
    return np.asarray(logits[0])  # [T, vocab]


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
@pytest.mark.parametrize("model_type", ["llama", "qwen2"])
def test_logits_match_transformers(model_type, tmp_path):
    cfg = _tiny_cfg(model_type)
    _write_checkpoint(tmp_path, cfg, with_bias=(model_type == "qwen2"))

    mod = _load_converter()
    m_path = str(tmp_path / "model.m")
    mod.convert(str(tmp_path), 0, m_path)  # f32: conversion is lossless

    tokens = [1, 17, 42, 9, 73, 5, 88, 2]
    ref = _hf_logits(str(tmp_path), tokens)
    got = _ours_logits(m_path, tokens)
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    # and the decision-level bar: identical next-token argmax per position
    assert np.argmax(got, axis=-1).tolist() == np.argmax(ref, axis=-1).tolist()
