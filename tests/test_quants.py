"""Quant codec tests — mirrors the coverage of src/nn/nn-cpu-ops-test.cpp:82-99
(Q40/Q80 round-trip tolerances) and converter/writer-test.py (golden Q40 bytes)."""

import numpy as np
import pytest

from distributed_llama_multiusers_tpu.quants.codec import (
    Q40_BLOCK_BYTES,
    Q80_BLOCK_BYTES,
    dequantize_q40,
    dequantize_q80,
    q40_to_planar,
    q80_to_planar,
    quantize_q40,
    quantize_q80,
)


def seeded(n, seed=123):
    rng = np.random.default_rng(seed)
    return (rng.random(n, dtype=np.float32) * 2 - 1).astype(np.float32)


def test_q80_roundtrip_tolerance():
    # reference tolerance: 0.01 for values in [-1.27, 1.27] scaled domain
    # (nn-cpu-ops-test.cpp testQuantizeQ80)
    x = seeded(32 * 64)
    back = dequantize_q80(quantize_q80(x))
    assert np.abs(back - x).max() < 0.01


def test_q40_roundtrip_tolerance():
    # reference tolerance: 0.13 (nn-cpu-ops-test.cpp testQuantizeQ40)
    x = seeded(32 * 64)
    back = dequantize_q40(quantize_q40(x))
    assert np.abs(back - x).max() < 0.13


def test_q40_block_layout():
    # Element j lives in low nibble of byte j, element j+16 in high nibble
    # (src/nn/nn-quants.cpp:215-224)
    x = np.arange(32, dtype=np.float32) - 16.0
    blocks = quantize_q40(x)
    assert blocks.shape == (1, Q40_BLOCK_BYTES)
    values, scales = q40_to_planar(blocks)
    d = scales[0]
    # max-abs element is -16 -> delta = -16/-8 = 2.0
    assert d == pytest.approx(2.0)
    back = dequantize_q40(blocks)
    assert np.abs(back - x).max() <= abs(d)


def test_q40_matches_reference_writer_semantics():
    # Re-implementation of converter/writer.py:29-53 in its original
    # formulation; our vectorized codec must produce identical bytes.
    import struct

    x = seeded(32 * 8, seed=7)
    groups = x.reshape(-1, 32)
    gmax = np.max(groups, axis=1)
    gmin = np.min(groups, axis=1)
    deltas = np.divide(np.where(-gmin > gmax, gmin, gmax), -8)
    deltas16 = deltas.astype(np.float16)
    ids = np.where(deltas != 0, 1.0 / deltas, 0)
    g = np.add(groups * ids[:, np.newaxis], 8.5)
    g = np.clip(g, 0, 15).astype(int)
    gLow = g[:, :16] & 0xF
    gHigh = (g[:, 16:] & 0xF) << 4
    gCombined = gLow | gHigh
    expect = b""
    for i in range(len(g)):
        expect += struct.pack("e16B", deltas16[i], *gCombined[i])

    assert quantize_q40(x).tobytes() == expect


def test_q80_converter_mode_matches_reference_writer_semantics():
    import struct

    x = seeded(32 * 8, seed=11)
    groups = x.reshape(-1, 32)
    gmax = np.max(groups, axis=1)
    gmin = np.min(groups, axis=1)
    gabsMax = np.where(-gmin > gmax, -gmin, gmax)
    deltas = gabsMax / 127.0
    deltas16 = deltas.astype(np.float16)
    ids = np.where(deltas != 0, 1.0 / deltas, 0)
    g8 = np.round(groups * ids[:, np.newaxis]).astype(np.int8)
    expect = b""
    for i in range(len(groups)):
        expect += struct.pack("e32b", deltas16[i], *g8[i])

    assert quantize_q80(x, mode="converter").tobytes() == expect


def test_q80_runtime_rounding_ties_away():
    # scale chosen so x/d hits exact .5: absmax 127 -> d=1, values .5 round to 1
    x = np.zeros(32, dtype=np.float32)
    x[0] = 127.0
    x[1] = 0.5
    x[2] = -0.5
    x[3] = 1.5
    blocks = quantize_q80(x, mode="runtime")
    values, scales = q80_to_planar(blocks)
    assert scales[0] == pytest.approx(1.0)
    assert values[0, 1] == 1  # roundf(0.5) = 1 (ties away)
    assert values[0, 2] == -1
    assert values[0, 3] == 2
    # converter mode: np.round(0.5) = 0 (ties to even)
    values_c, _ = q80_to_planar(quantize_q80(x, mode="converter"))
    assert values_c[0, 1] == 0
    assert values_c[0, 3] == 2


def test_zero_block():
    x = np.zeros(64, dtype=np.float32)
    assert np.all(dequantize_q40(quantize_q40(x)) == 0)
    assert np.all(dequantize_q80(quantize_q80(x)) == 0)
