"""Fleet-wide distributed tracing tests (telemetry/tracectx.py — ISSUE 20).

Four layers:

- **wire units** — the ``X-DLlama-Trace`` format round-trips, malformed
  and all-zero ids are refused (never 400d: callers mint instead), and
  ``child()`` keeps the trace id while re-minting the hop span id.
- **aggregation units** — ``PhaseAccumulator`` validates/cleans records,
  ``LabelledHistogram`` renders one labelled metric family and answers
  per-label quantiles, the span ring's ``since=`` cursor and per-track
  drop counts behave, and ``merge_chrome_traces`` applies clock-offset
  corrections VISIBLY (stamped per event, never silent).
- **replica surfaces** — a client header rides a request into the
  replica's summary and span ring; ``/trace?trace_id=&since=`` filters
  over real HTTP; ``/stats`` reports ring occupancy.
- **THE pins** — a stream spliced across a mid-flight replica kill keeps
  ONE trace id end to end, and ``GET /trace/<id>`` on the router returns
  ONE loadable Perfetto timeline holding the router's route span, the
  migration gap, and both replicas' spans; the disagg prefill→decode
  hand-off rejoins the same trace on the decode side via the ticket.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from distributed_llama_multiusers_tpu.fleet import FleetRouter
from distributed_llama_multiusers_tpu.runtime.scheduler import (
    ContinuousBatchingScheduler,
)
from distributed_llama_multiusers_tpu.serving import StreamRegistry
from distributed_llama_multiusers_tpu.server import ApiServer
from distributed_llama_multiusers_tpu.telemetry.metrics import MetricsRegistry
from distributed_llama_multiusers_tpu.telemetry.spans import (
    SpanEvent,
    SpanTracer,
)
from distributed_llama_multiusers_tpu.telemetry.trace import (
    chrome_trace,
    merge_chrome_traces,
    tracer_chrome_trace,
)
from distributed_llama_multiusers_tpu.telemetry.tracectx import (
    PHASE_KEYS,
    TRACE_HEADER,
    PhaseAccumulator,
    TraceContext,
    trace_id_of,
)
from distributed_llama_multiusers_tpu.tokenizer import TemplateType
from distributed_llama_multiusers_tpu.utils import faults
from distributed_llama_multiusers_tpu.utils.testing import (
    CharStreamTokenizer,
    MockAsyncEngine,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# wire format units
# ---------------------------------------------------------------------------


def test_wire_mint_parse_round_trip():
    ctx = TraceContext.mint()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    wire = ctx.to_header()
    assert wire == f"{ctx.trace_id}-{ctx.span_id}"
    back = TraceContext.parse(wire)
    assert back == ctx
    # uppercase and padding normalise (header values survive proxies)
    assert TraceContext.parse("  " + wire.upper() + " ") == ctx
    # child: same trace, fresh hop span
    kid = ctx.child()
    assert kid.trace_id == ctx.trace_id
    assert kid.span_id != ctx.span_id
    assert trace_id_of(wire) == ctx.trace_id


def test_parse_rejects_malformed_and_zero_ids():
    bad = [
        None, "", "not-a-trace", "deadbeef", "-".join(["ab" * 16] * 2),
        "g" * 32 + "-" + "0" * 16,       # non-hex
        "0" * 32 + "-" + "1234567890abcdef",  # zero trace id
        "a" * 32 + "-" + "0" * 16,       # zero span id
        "a" * 32 + "1234567890abcdef",   # missing dash
        "a" * 31 + "-" + "1" * 16,       # short trace id
    ]
    for v in bad:
        assert TraceContext.parse(v) is None, v
        assert trace_id_of(v) is None, v


def test_accept_honours_valid_mints_otherwise():
    ctx = TraceContext.mint()
    assert TraceContext.accept(ctx.to_header()) == ctx
    minted = TraceContext.accept("garbage header")
    assert minted.trace_id != ctx.trace_id
    assert TraceContext.parse(minted.to_header()) == minted
    # two mints never collide on the ids that matter
    assert TraceContext.accept(None).trace_id != minted.trace_id


# ---------------------------------------------------------------------------
# aggregation units
# ---------------------------------------------------------------------------


def test_phase_accumulator_cleans_and_aggregates():
    acc = PhaseAccumulator()
    assert acc.observe(None) is None
    assert acc.observe("nope") is None
    assert acc.observe({"unknown_key": 3.0}) is None
    clean = acc.observe({
        "ttft_ms": 12.5, "decode_ms": 40.0,
        "queue_wait_ms": -1.0,           # negative: dropped
        "prefill_ms": "fast",            # non-numeric: dropped
        "bogus": 9.0,                    # unknown: dropped
    })
    assert clean == {"ttft_ms": 12.5, "decode_ms": 40.0}
    acc.observe({"ttft_ms": 7.5})
    snap = acc.snapshot()
    assert snap["phase_records"] == 2
    assert snap["phase_counts"]["ttft_ms"] == 2
    assert snap["phase_sum_ms"]["ttft_ms"] == pytest.approx(20.0)
    assert snap["phase_counts"]["decode_ms"] == 1
    assert set(clean) <= set(PHASE_KEYS)


def test_labelled_histogram_render_and_quantile():
    reg = MetricsRegistry()
    h = reg.labelled_histogram(
        "dllama_request_phase_seconds", "per-request phase attribution",
    )
    assert reg.labelled_histogram("dllama_request_phase_seconds") is h
    for v in (0.010, 0.020, 0.040):
        h.observe(v, phase="ttft_ms")
    h.observe(1.5, phase="decode_ms")
    assert h.quantile(0.5, phase="ttft_ms") == pytest.approx(0.020, rel=0.6)
    assert h.quantile(0.5, phase="never_seen") is None
    counts, total, n = h.snapshot(phase="ttft_ms")
    assert n == 3 and total == pytest.approx(0.070)
    assert sum(counts) == 3
    text = "\n".join(h.render())
    assert "# TYPE dllama_request_phase_seconds histogram" in text
    assert 'phase="ttft_ms"' in text and 'phase="decode_ms"' in text
    assert 'le="+Inf"' in text
    assert 'dllama_request_phase_seconds_count{phase="ttft_ms"} 3' in text
    # the registry renders the family exactly once
    assert reg.render().count("# TYPE dllama_request_phase_seconds") == 1


def test_span_ring_since_cursor_and_per_track_drops():
    tracer = SpanTracer(capacity=3)
    t = tracer.now()
    tracer.slice("a", "lane0", t)
    tracer.slice("b", "lane0", t)
    tracer.slice("c", "queue", t)
    doc = tracer_chrome_trace(tracer)
    cursor = doc["cursor"]
    assert cursor == 3
    # nothing newer: the incremental poll is empty but keeps the cursor
    doc2 = tracer_chrome_trace(tracer, since=cursor)
    assert doc2["cursor"] == cursor
    assert [e for e in doc2["traceEvents"] if e["ph"] != "M"] == []
    # overflow: the two oldest (both lane0) evict, attributed per track
    tracer.slice("d", "queue", t)
    tracer.slice("e", "queue", t)
    counts = tracer.counts()
    assert counts["trace_events_recorded"] == 5
    assert counts["trace_events_dropped"] == 2
    assert counts["trace_events_dropped_by_track"] == {"lane0": 2}
    assert counts["trace_events_buffered"] == 3
    # since= returns only the post-cursor events
    newer = tracer.snapshot(since=cursor)
    assert [e.name for e in newer] == ["d", "e"]
    # trace_id filter: only args-tagged events survive
    tracer.slice("f", "queue", t, args={"trace_id": "ab" * 16})
    assert [e.name for e in tracer.snapshot(trace_id="ab" * 16)] == ["f"]


def test_clock_skew_merge_corrects_and_stamps():
    """Two rings on skewed fake clocks: replica B's raw timestamps LOOK
    earlier than A's, but with its known offset applied it lands later —
    and the correction is stamped on every migrated event, not silently
    absorbed."""
    ev = lambda name, ts: SpanEvent(name, "X", ts, 0.010, "lane0")
    doc_a = chrome_trace([ev("generate", 1.000)], origin=0.0)
    doc_b = chrome_trace([ev("generate", 0.400)], origin=0.0)
    merged = merge_chrome_traces([
        ("a", doc_a, 0.0, 0.0),
        ("b", doc_b, 700_000.0, 1_500.0),
    ])
    # loadable: plain JSON, fleet process name, per-source track rows
    merged = json.loads(json.dumps(merged))
    events = merged["traceEvents"]
    procs = [e for e in events
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert [p["args"]["name"] for p in procs] == ["dllama-fleet"]
    tracks = {e["args"]["name"] for e in events
              if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"a/lane0", "b/lane0"} <= tracks
    slices = [e for e in events if e["ph"] == "X"]
    by_src = {e["args"]["span_source"]: e for e in slices}
    assert by_src["a"]["ts"] == pytest.approx(1_000_000.0)
    assert by_src["b"]["ts"] == pytest.approx(1_100_000.0)  # 0.4s + offset
    # corrected ordering: a before b despite b's smaller raw ts
    assert [e["args"]["span_source"] for e in slices] == ["a", "b"]
    assert by_src["b"]["args"]["clock_offset_us"] == pytest.approx(700_000.0)
    assert by_src["b"]["args"]["clock_uncertainty_us"] == pytest.approx(
        1_500.0
    )
    assert by_src["a"]["args"]["clock_offset_us"] == 0.0


# ---------------------------------------------------------------------------
# replica surfaces over real HTTP
# ---------------------------------------------------------------------------


class _Tok(CharStreamTokenizer):
    def decode(self, token):
        return f"[{token}]"


def _replica(rid, n_lanes=2, step_s=0.005, paged=False, role="mixed"):
    kw = {}
    if paged:
        kw = dict(paged=True, kv_page_size=16, kv_pool_pages=128,
                  kv_max_parked=32)
    engine = MockAsyncEngine(n_lanes=n_lanes, max_chunk=8,
                             content_keyed=True, step_s=step_s, **kw)
    sched = ContinuousBatchingScheduler(
        engine, _Tok(64, max_chars=96),
        speculative=False, prefix_min_tokens=0, multi_step=0,
    )
    sched.start()
    registry = StreamRegistry(grace_s=30.0)
    api = ApiServer(sched, _Tok(64, max_chars=96), model_name="tracefleet",
                    template_type=TemplateType.LLAMA2, resume=registry,
                    replica_id=rid, role=role)
    httpd = api.serve(host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return {"api": api, "engine": engine, "sched": sched,
            "registry": registry, "httpd": httpd,
            "base": f"127.0.0.1:{httpd.server_address[1]}", "rid": rid}


def _stop_replica(r):
    try:
        r["httpd"].shutdown()
    finally:
        if r["registry"] is not None:
            r["registry"].close()
        try:
            r["sched"].stop()
        except RuntimeError:
            pass


def _get_json(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def _router(replicas, **kw):
    router = FleetRouter(
        {r["rid"]: r["base"] for r in replicas},
        scrape_interval_s=kw.pop("scrape_interval_s", 0.1),
        **kw,
    ).start()
    httpd = router.serve(host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    router.scrape_once()
    return router, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _stream(url, body, headers=None, on_delta=None, timeout=120):
    """(text, terminal payload, response headers) for one SSE POST."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    texts, term = [], None
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        resp_headers = dict(resp.headers)
        for line in resp:
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            p = json.loads(line[6:])
            if "error" in p:
                term = p
                continue
            ch = p.get("choices", [{}])[0]
            if ch.get("finish_reason") is None:
                texts.append(ch.get("text", ""))
                if on_delta is not None:
                    on_delta(len(texts))
            else:
                term = p
    return "".join(texts), term, resp_headers


def test_replica_honours_trace_header_and_filters_ring():
    r = _replica("tr1")
    ctx = TraceContext.mint()
    try:
        url = f"http://{r['base']}/v1/completions"
        # non-streaming: the summary surfaces the trace id
        req = urllib.request.Request(
            url,
            data=json.dumps({"prompt": "trace header round trip",
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: ctx.to_header()},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = json.loads(resp.read())
        assert body["summary"]["trace_id"] == ctx.trace_id
        assert set(PHASE_KEYS) <= set(body["summary"]["phases"])
        # the span ring tagged this request's events with trace + replica
        doc, _ = _get_json(
            f"http://{r['base']}/trace?trace_id={ctx.trace_id}"
        )
        events = [e for e in doc["traceEvents"] if e["ph"] != "M"]
        assert events, "no ring events carried the trace id"
        assert {e["args"]["trace_id"] for e in events} == {ctx.trace_id}
        assert {e["args"]["replica"] for e in events} == {"tr1"}
        assert "generate" in {e["name"] for e in events}
        # incremental poll: pass the cursor back, get nothing twice
        full, _ = _get_json(f"http://{r['base']}/trace")
        inc, _ = _get_json(f"http://{r['base']}/trace?since={full['cursor']}")
        assert [e for e in inc["traceEvents"] if e["ph"] != "M"] == []
        assert inc["cursor"] == full["cursor"]
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://{r['base']}/trace?since=nonsense", timeout=10
            )
        assert e.value.code == 400
        # /stats surfaces ring occupancy + per-track drop attribution
        stats, _ = _get_json(f"http://{r['base']}/stats")
        assert stats["trace_events_recorded"] >= len(events)
        assert stats["trace_events_dropped"] == 0
        assert isinstance(stats["trace_events_dropped_by_track"], dict)
        # a malformed header is IGNORED, never an error: the request
        # runs untraced (replicas don't mint; the router does)
        req = urllib.request.Request(
            url,
            data=json.dumps({"prompt": "malformed header ignored",
                             "max_tokens": 2}).encode(),
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: "not a context"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = json.loads(resp.read())
        assert "trace_id" not in body["summary"]
    finally:
        _stop_replica(r)


# ---------------------------------------------------------------------------
# router: minting, echo, phase aggregation
# ---------------------------------------------------------------------------


def test_router_mints_echoes_and_aggregates_phases():
    r = _replica("ag1")
    router, rhttpd, rbase = _router([r])
    try:
        body = {"prompt": "router trace minting probe " * 4,
                "max_tokens": 6, "stream": True}
        text, term, headers = _stream(rbase + "/v1/completions", body)
        assert text and term["choices"][0]["finish_reason"] == "length"
        # no client header: the router MINTED a context and echoed it
        minted = TraceContext.parse(headers.get(TRACE_HEADER))
        assert minted is not None
        phases = term["summary"]["phases"]
        assert set(PHASE_KEYS) <= set(phases)
        assert phases["ttft_ms"] > 0
        assert phases["migration_gap_ms"] == 0.0
        # terminal phases fold into the router-side aggregation: the
        # /stats sums reconcile with the record the client just read
        stats = router.handle_stats()
        assert stats["phase_records"] == 1
        assert stats["phase_sum_ms"]["ttft_ms"] == pytest.approx(
            phases["ttft_ms"], abs=0.01
        )
        assert stats["trace_events_recorded"] >= 1  # the route span
        assert "ag1" in stats["clock_offset_us"]
        assert stats["clock_uncertainty_us"]["ag1"] >= 0.0
        # /metrics: ONE labelled histogram family, count == records
        metrics = router.handle_metrics()
        assert 'dllama_request_phase_seconds_count{phase="ttft_ms"} 1' \
            in metrics
        assert 'dllama_request_phase_seconds_bucket{phase="decode_ms"' \
            in metrics
        # a client-supplied context is honoured end to end: echoed trace
        # id matches, and the replica's summary carries it back through
        ctx = TraceContext.mint()
        _, term2, headers2 = _stream(
            rbase + "/v1/completions", body,
            headers={TRACE_HEADER: ctx.to_header()},
        )
        # the echo is the CLIENT'S context verbatim (the id it will
        # correlate on); the per-hop child contexts ride upstream only
        assert TraceContext.parse(headers2.get(TRACE_HEADER)) == ctx
        assert term2["summary"]["trace_id"] == ctx.trace_id
        # /trace/<id> input validation: non-hex ids are 400, not crashes
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(rbase + "/trace/nothex", timeout=10)
        assert e.value.code == 400
    finally:
        router.close()
        rhttpd.shutdown()
        _stop_replica(r)


# ---------------------------------------------------------------------------
# THE pins: one trace id across a mid-stream kill / a disagg hand-off
# ---------------------------------------------------------------------------


def test_trace_survives_migration_one_merged_timeline():
    """THE pin (acceptance criterion): a stream spliced across a replica
    kill keeps ONE trace id, and the router's ``GET /trace/<id>`` merges
    the router's route span, the migration gap, and BOTH replicas' spans
    into one loadable Perfetto doc — with the clock correction stamped
    per event. The kill stops the scheduler only (force-cancel → typed
    cancelled → migrate) and leaves the victim's HTTP surface up, the
    orderly-drain shape where the dead replica's ring is still readable;
    a replica that vanished entirely contributes nothing by design."""
    a, b = _replica("v1"), _replica("v2")
    router, rhttpd, rbase = _router([a, b])
    killed = []
    ctx = TraceContext.mint()
    try:
        # > 256 prompt chars: a full affinity block, so the traced rerun
        # lands on the same replica the reference run named
        body = {"prompt": "trace migration pin " * 20, "max_tokens": 30,
                "stream": True}
        ref_text, _, ref_headers = _stream(rbase + "/v1/completions", body)
        source = ref_headers.get("X-DLlama-Replica")

        def kill_source(n_deltas):
            if n_deltas == 5 and not killed:
                victim = a if source == "v1" else b
                killed.append(victim)
                victim["sched"].stop()

        text, term, headers = _stream(
            rbase + "/v1/completions", body,
            headers={TRACE_HEADER: ctx.to_header()}, on_delta=kill_source,
        )
        assert killed, "the kill never fired"
        survivor = "v2" if killed[0] is a else "v1"
        assert text == ref_text  # byte-identical across the splice
        assert term["choices"][0]["finish_reason"] == "length"
        assert router.migrations_ok == 1
        # one trace id end to end: echoed header, decode-side summary
        assert TraceContext.parse(
            headers.get(TRACE_HEADER)
        ).trace_id == ctx.trace_id
        assert term["summary"]["trace_id"] == ctx.trace_id
        # the router stamped the gap ONLY IT saw into the terminal record
        gap_ms = term["summary"]["phases"]["migration_gap_ms"]
        assert gap_ms > 0.0
        stats = router.handle_stats()
        assert stats["phase_sum_ms"]["migration_gap_ms"] == pytest.approx(
            gap_ms, abs=0.01
        )

        # ONE merged timeline over HTTP, loadable Chrome-trace JSON
        doc, _ = _get_json(rbase + f"/trace/{ctx.trace_id}")
        doc = json.loads(json.dumps(doc))
        events = doc["traceEvents"]
        procs = [e for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"]
        assert [p["args"]["name"] for p in procs] == ["dllama-fleet"]
        real = [e for e in events if e["ph"] != "M"]
        assert real and all(
            e["args"]["trace_id"] == ctx.trace_id for e in real
        )
        sources = {e["args"]["span_source"] for e in real}
        assert {"router", source, survivor} <= sources
        names = {(e["args"]["span_source"], e["name"]) for e in real}
        assert ("router", "route") in names
        assert ("router", "migration.gap") in names
        assert (survivor, "generate") in names  # the spliced-to stream
        gap = next(e for e in real if e["name"] == "migration.gap")
        assert gap["args"]["from"] == source
        assert gap["args"]["to"] == survivor
        assert gap["args"]["kind"] == "migration"
        # replica events landed on the router timebase with the estimate
        # stamped — measured ordering stays distinguishable from aligned
        for e in real:
            assert "clock_offset_us" in e["args"]
            assert e["args"]["clock_uncertainty_us"] >= 0.0
        tracks = {e["args"]["name"] for e in events
                  if e["ph"] == "M" and e["name"] == "thread_name"}
        assert any(t.startswith("router/") for t in tracks)
        assert any(t.startswith(f"{survivor}/") for t in tracks)
    finally:
        router.close()
        rhttpd.shutdown()
        for r in (a, b):
            _stop_replica(r)


def test_disagg_handoff_rejoins_trace_on_decode_side():
    """The prefill→decode hand-off carries the context on every admin
    hop AND inside the migration ticket: the decode replica's session
    rejoins the ORIGINAL trace (its summary names it), and the fleet
    timeline shows the transfer as a ``disagg.handoff`` row between the
    two replicas' spans."""
    p = _replica("p0", paged=True, role="prefill")
    d = _replica("d0", paged=True, role="decode")
    router, rhttpd, rbase = _router([p, d], long_prompt_chars=120)
    ctx = TraceContext.mint()
    try:
        body = {"prompt": "disagg trace pin prompt " * 12,  # > 120 chars
                "max_tokens": 20, "stream": True}
        text, term, headers = _stream(
            rbase + "/v1/completions", body,
            headers={TRACE_HEADER: ctx.to_header()},
        )
        assert text and term["choices"][0]["finish_reason"] == "length"
        assert router.disagg_handoffs_ok == 1
        # the decode-side session REJOINED the original trace
        assert term["summary"]["trace_id"] == ctx.trace_id
        assert TraceContext.parse(
            headers.get(TRACE_HEADER)
        ).trace_id == ctx.trace_id
        doc, _ = _get_json(rbase + f"/trace/{ctx.trace_id}")
        real = [e for e in doc["traceEvents"] if e["ph"] != "M"]
        sources = {e["args"]["span_source"] for e in real}
        assert {"router", "p0", "d0"} <= sources
        hand = next(e for e in real if e["name"] == "disagg.handoff")
        assert hand["args"]["from"] == "p0"
        assert hand["args"]["to"] == "d0"
        names = {(e["args"]["span_source"], e["name"]) for e in real}
        assert ("d0", "generate") in names
    finally:
        router.close()
        rhttpd.shutdown()
        _stop_replica(p)
        _stop_replica(d)
